"""serve — the always-on campaign serving daemon.

The CLI over ``stencil_tpu/serve/``: point it at a ``--serve-dir`` and
it serves forever — producers drop job JSONs into
``<serve-dir>/jobs/incoming/`` (atomically: write a tmp file, rename;
``scripts/serve_loadgen.py`` is the reference producer), the daemon
admits them against per-tenant ``--quota`` and ledger-priced deadlines,
packs batch slots via the CAPACITY ENGINE (on by default: stride-
weighted fairness with aging, scored cross-bucket packing, elastic slot
width over ``--slot-min``/``--slot-max``, priced chunk-boundary
preemption — each individually defeatable via ``--no-fairness`` /
``--no-packing`` / ``--no-preempt`` and fixed width by default),
backfills retired lanes from the live queue MID-SLOT (continuous
batching — no slot-wide barrier), and streams each result into
``<serve-dir>/results/<job>.json`` the moment the tenant retires.

Lifecycle:

- **SIGTERM** drains gracefully: intake stops, live lanes park as
  revivable snapshots at the next segment boundary, the queue persists
  to ``serve-state.json``, the daemon exits 0.
- **SIGKILL / crash** loses nothing: restart the same command (the PR 3
  watchdog ladder does this automatically) and the daemon revives every
  admitted-but-unserved job from ``serve-state.json`` — running jobs
  resume from their newest snapshot (bit-identical by the ckpt
  contract), retired jobs are NEVER re-run, replayed job files are
  quarantined as duplicates.
- ``--max-idle-s`` / ``--max-wall-s`` bound a session (CI gates, bench
  legs); 0 means serve until drained.

Watch it: ``report --status <status-file> --follow`` renders the live
queue line (depth/admitted/rejected/backfills) next to the lane table.

Usage: python -m stencil_tpu.apps.serve --serve-dir /srv/stencil \
           --cpu 8 --slot 4 --quota 2 --max-idle-s 30 \
           --metrics-out serve.jsonl --status-file status.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
from typing import Optional

import jax

from ..obs import telemetry
from ..utils import logging as log

# injected-kill hook (CI serve gate / tests): after the Nth tenant
# retires — serve-state.json durable, the result streamed — die hard
# with rc 17 (the ckpt kill hook's rc: "killed on purpose, revive me"),
# so the gate can prove a revived daemon finishes the queue without
# re-running the retired work
KILL_ENV = "STENCIL_SERVE_KILL_AFTER_RETIRE"


def build_scheduler(args, sentinel=None, status=None):
    from ..serve import ServeScheduler

    devices = jax.devices()[: args.cpu] if args.cpu else jax.devices()
    weights = {}
    for part in (args.fair_weights or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise SystemExit(f"bad --fair-weights entry {part!r} "
                             "(want CLASS=WEIGHT)")
        k, v = part.split("=", 1)
        weights[k.strip()] = float(v)
    sched = ServeScheduler(
        args.serve_dir, args.slot,
        quota=args.quota, admission_ledger=args.admission_ledger or None,
        poll_s=args.poll_s, max_idle_s=args.max_idle_s,
        max_wall_s=args.max_wall_s,
        slot_min=args.slot_min or None, slot_max=args.slot_max or None,
        packing=not args.no_packing, preempt=not args.no_preempt,
        fairness=not args.no_fairness, fair_weights=weights or None,
        aging_s=args.aging_s,
        preempt_cost_chunks=args.preempt_cost_chunks,
        devices=devices, chunk=args.chunk,
        ckpt_every=args.ckpt_every, ckpt_keep=args.ckpt_keep,
        health_every=args.health_every, max_abs=args.max_abs or None,
        max_rollbacks=args.max_rollbacks,
        rollback_backoff=args.rollback_backoff,
        sentinel=sentinel, status=status,
    )
    if args.replan:
        # the campaign's between-slot hot-swap, with serving's extra
        # trigger: SLO pressure (deadline-at-risk vs the online p99)
        # latches the controller exactly like a sentinel anomaly; the
        # re-tune targets the LAST slot's bucket and persists into
        # --plan-db (force=True, static-only — slots must not stall)
        from ..campaign.driver import WORKLOADS
        from ..geometry import Dim3, Radius
        from ..plan.replan import ReplanController

        def retune_fn():
            from ..plan.autotune import autotune as _plan_autotune

            bucket = sched._last_bucket
            if bucket is None:
                raise ValueError("no slot has run yet; nothing to retune")
            (size, dtype, workload) = bucket
            wl = WORKLOADS[workload]
            nq = len(wl.quantity_names(dtype))
            res = _plan_autotune(
                Dim3(size[0], size[1], size[2]),
                Radius.constant(wl.default_radius),
                [dtype] * nq, devices=devices,
                db_path=args.plan_db or None, probe=False, force=True,
            )
            return res.choice

        controller = ReplanController(
            retune_fn, lambda choice, st: None, sentinel=sentinel)
        if sentinel is not None:
            sentinel.on_replan = controller.request
        sched.replan = controller
    return sched


def install_kill_hook(sched) -> None:
    """Arm the CI kill hook when the env var names a retirement count."""
    kill_after = int(os.environ.get(KILL_ENV, "0") or 0)
    if kill_after <= 0:
        return
    orig = sched._on_result

    def killing(r):
        orig(r)
        if sched._retired_run >= kill_after:
            log.warn(f"{KILL_ENV}: dying after {sched._retired_run} "
                     "retirement(s)")
            os._exit(17)

    sched._on_result = killing


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(
        description="always-on campaign serving daemon")
    p.add_argument("--serve-dir", required=True,
                   help="service root: jobs/{incoming,claimed,bad}, "
                        "campaign/ (slots + tenant snapshots), results/, "
                        "serve-state.json")
    p.add_argument("--slot", type=int, default=4,
                   help="batch-slot size B (lanes per compiled program); "
                        "with --slot-min/--slot-max this is only the "
                        "elastic ladder's default")
    p.add_argument("--slot-min", type=int, default=0,
                   help="elastic width floor: each slot is sized to its "
                        "bucket's queue depth on a power-of-two ladder "
                        "from --slot-min to --slot-max (0 = --slot, "
                        "i.e. fixed width)")
    p.add_argument("--slot-max", type=int, default=0,
                   help="elastic width ceiling; a mid-slot surge grows "
                        "the running slot at a chunk boundary "
                        "(park-repartition-revive, bit-identical) "
                        "(0 = --slot)")
    p.add_argument("--fair-weights", default="",
                   help="served-share weights as CLASS=WEIGHT commas, "
                        "e.g. 'high=8,normal=4,low=1' (the default); "
                        "shares are stride-scheduled, so doubling a "
                        "weight can only raise that class's share")
    p.add_argument("--aging-s", type=float, default=30.0,
                   help="seconds of queue wait that promote a job one "
                        "priority class; a job waiting past "
                        "aging_s*(rank+1) leads the next slot outright "
                        "— the hard no-starvation bound (0 = no aging)")
    p.add_argument("--no-fairness", action="store_true",
                   help="strict priority order (PR 19): no weighted "
                        "shares, no aging — sustained high load may "
                        "starve low")
    p.add_argument("--no-packing", action="store_true",
                   help="head-of-queue bucket selection instead of the "
                        "scored cross-bucket packing pass")
    p.add_argument("--no-preempt", action="store_true",
                   help="never park a running slot for an infeasible "
                        "high arrival")
    p.add_argument("--preempt-cost-chunks", type=float, default=1.0,
                   help="priced resume cost per victim, in fused chunks "
                        "of its bucket's p99 — preemption (and mid-slot "
                        "growth) fires only when the priced gain "
                        "exceeds this")
    p.add_argument("--chunk", type=int, default=2,
                   help="fused steps per dispatch")
    p.add_argument("--quota", type=int, default=0,
                   help="per-tenant cap on live (queued+running) jobs; an "
                        "over-quota job is DEFERRED and promoted when one "
                        "of the tenant's jobs retires (0 = unlimited)")
    p.add_argument("--admission-ledger", default="",
                   help="performance ledger (obs/ledger.py) seeding "
                        "per-bucket p99 deadline pricing; the daemon "
                        "appends its own serve.step_p99_ms entries back "
                        "at exit, so pricing survives restarts")
    p.add_argument("--poll-s", type=float, default=0.2,
                   help="idle intake poll interval")
    p.add_argument("--max-idle-s", type=float, default=0.0,
                   help="exit after this long with an empty queue "
                        "(0 = serve until drained)")
    p.add_argument("--max-wall-s", type=float, default=0.0,
                   help="total wall budget; reaching it drains gracefully "
                        "(0 = unbounded)")
    p.add_argument("--ckpt-every", type=int, default=2,
                   help="checkpoint every active lane every N slot steps — "
                        "the revival substrate (0 = only final/park "
                        "snapshots; a SIGKILLed daemon then replays whole "
                        "tenants instead of resuming mid-flight)")
    p.add_argument("--ckpt-keep", type=int, default=3)
    p.add_argument("--health-every", type=int, default=0,
                   help="per-lane health-check cadence in slot steps "
                        "(default: every fused chunk)")
    p.add_argument("--max-abs", type=float, default=0.0,
                   help="divergence ceiling on max|u| (0 = none)")
    p.add_argument("--max-rollbacks", type=int, default=2)
    p.add_argument("--rollback-backoff", type=float, default=0.05)
    p.add_argument("--replan", action="store_true",
                   help="between-slot plan hot-swap: SLO pressure "
                        "(deadline-at-risk vs the bucket's online p99) or "
                        "a sentinel anomaly latches a re-tune of the last "
                        "slot's bucket, persisted into --plan-db")
    p.add_argument("--plan-db", default="",
                   help="plan DB the --replan re-tune persists into")
    p.add_argument("--cpu", type=int, default=0,
                   help="force N virtual CPU devices")
    from ._bench_common import (add_live_flags, add_metrics_flags,
                                canonicalize_live_config, finish_live,
                                finish_metrics, make_live, start_metrics)
    add_metrics_flags(p)
    add_live_flags(p)
    args = p.parse_args(argv)
    if args.replan and not args.plan_db:
        # same contract as the campaign: the swap's APPLY is the DB
        # install — without a DB it would install nothing
        p.error("--replan persists the re-tuned plan into --plan-db; "
                "pass one (the swap would otherwise install nothing)")
    try:
        canonicalize_live_config(args)
    except (OSError, ValueError) as e:
        p.error(f"bad --live-config: {e}")

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    # jobs choose their dtype at drop time; a float64 job must not be
    # silently downcast by a daemon started before it existed
    jax.config.update("jax_enable_x64", True)
    rec = start_metrics(args, "serve")
    sentinel, status = make_live(args, rec, "serve")

    sched = build_scheduler(args, sentinel=sentinel, status=status)
    install_kill_hook(sched)
    # SIGTERM = drain: stop claiming, park lanes at the next segment
    # boundary, persist the queue, exit 0 (the systemd/k8s stop contract)
    signal.signal(signal.SIGTERM,
                  lambda signum, frame: sched.request_drain("sigterm"))

    summary = sched.serve()
    out = {
        "app": "serve",
        "serve_dir": args.serve_dir,
        "slot": args.slot,
        "quota": args.quota,
        "devices": len(sched.devices),
    }
    out.update({k: v for k, v in summary.items() if k != "results"})
    if isinstance(out.get("tenants_per_hour"), float):
        out["tenants_per_hour"] = round(out["tenants_per_hour"], 3)
    print(json.dumps(out, default=str))
    finish_live(rec, sentinel, status, outcome=summary["outcome"])
    finish_metrics(rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
