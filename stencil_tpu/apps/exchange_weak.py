"""exchange-weak — pure halo-exchange benchmark, weak-scaled.

TPU-native port of the reference benchmark (reference: bin/exchange_weak.cu):
radius-3 halos, four float quantities, domain weak-scaled by the prime
factors of the device count, trimean over N exchanges. CSV row matches the
reference header (bin/exchange_weak.cu:184-196):

  exchange,<method>,<naive>,x,y,z,s,ldx,ldy,ldz,<bytes>,iters,gpus,nodes,ranks,trimean(s)

Usage: python -m stencil_tpu.apps.exchange_weak 512 512 512 30 [--naive|--random]
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax

from ..geometry import Dim3
from ..parallel import Method
from ._bench_common import (
    add_metrics_flags, placement_from_flags, start_metrics, time_exchange,
)
from .jacobi3d import weak_scale
from ..geometry import Radius
from ..utils import logging as log


def run(
    x: int,
    y: int,
    z: int,
    iters: int = 30,
    naive: bool = False,
    random_: bool = False,
    method: Method = Method.AXIS_COMPOSED,
    devices=None,
    weak: bool = True,
    radius: int = 3,
    prefix: str = "",
    chunk: int = 10,
) -> dict:
    devices = list(devices) if devices is not None else jax.devices()
    size = weak_scale(x, y, z, len(devices)) if weak else Dim3(x, y, z)
    r = time_exchange(
        size,
        Radius.constant(radius),
        iters,
        method=method,
        devices=devices,
        placement=placement_from_flags(naive, random_),
        quantities=4,
        prefix=prefix,
        chunk=chunk,
    )
    r.update(
        app="exchange",
        method=method.value,
        naive=int(naive),
        x=size.x,
        y=size.y,
        z=size.z,
        iters=iters,
        nodes=jax.process_count(),
        ranks=jax.process_count(),
    )
    return r


def csv_row(r: dict) -> str:
    ld = r["local_size"]
    return (
        f"{r['app']},{r['method']},{r['naive']},{r['x']},{r['y']},{r['z']},"
        f"{r['x'] * r['y'] * r['z']},{ld.x},{ld.y},{ld.z},"
        f"{r['bytes_logical']},{r['iters']},{r['devices']},{r['nodes']},"
        f"{r['ranks']},{r['trimean_s']:e}"
    )


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="weak-scaled halo exchange benchmark")
    p.add_argument("x", type=int)
    p.add_argument("y", type=int)
    p.add_argument("z", type=int)
    p.add_argument("iters", type=int)
    p.add_argument("--prefix", default="")
    p.add_argument("--naive", action="store_true", help="Trivial placement")
    p.add_argument("--random", action="store_true", help="IntraNodeRandom placement")
    p.add_argument("--direct26", action="store_true")
    p.add_argument("--cpu", type=int, default=0)
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    start_metrics(args, "exchange_weak")
    r = run(
        args.x,
        args.y,
        args.z,
        iters=args.iters,
        naive=args.naive,
        random_=args.random,
        method=Method.DIRECT26 if args.direct26 else Method.AXIS_COMPOSED,
        prefix=args.prefix,
    )
    print(csv_row(r))
    log.info(f"exchange {r['gb_per_s']:.2f} GB/s logical halo bytes")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
