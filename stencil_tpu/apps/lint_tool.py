"""lint_tool — the static-analysis front end (stencil_tpu/analysis/).

Subcommands, sharing perf_tool's gate semantics (exit 1 on new
findings / failed checks, exit 2 when nothing was analyzed — a
validate-nothing run must never read as a pass):

- ``lint``        AST lint of the repo's own contracts (astlint.py):
                  rule registry, inline ``# lint: disable=<rule>``
                  suppressions, committed fingerprint baseline.
                  ``--changed`` restricts to ``git diff --name-only``
                  files (the fast pre-commit path).
- ``verify-plan`` ExchangePlan-IR vs compiled-HLO conformance sweep
                  (verify_plan.py): per-config census/byte/DMA
                  cross-checks; infeasible configs (plan/cost.feasible)
                  are skipped loudly, an all-skipped sweep exits 2.
- ``jit-audit``   step-loop audit (jit_audit.py): transfer_guard +
                  compile counter around post-warmup jacobi chunks;
                  ``--inject recompile|host-sync`` are the
                  must-fail fixtures.
- ``all``         the full suite (what scripts/ci_static_gate.py runs).

``--json`` prints one machine-readable document; ``--metrics-out``
records the schema-valid ``analysis.*`` telemetry vocabulary.

Runs under ``JAX_PLATFORMS=cpu`` everywhere; ``--cpu N`` forces N
virtual CPU devices (like the bench apps).

Usage:
  python -m stencil_tpu.apps.lint_tool lint
  python -m stencil_tpu.apps.lint_tool lint --changed
  python -m stencil_tpu.apps.lint_tool verify-plan --cpu 8
  python -m stencil_tpu.apps.lint_tool jit-audit --cpu 8
  python -m stencil_tpu.apps.lint_tool all --cpu 8 --json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = "lint-baseline.json"


def _parse_partitions(text: str) -> List[tuple]:
    out = []
    for tok in text.split(","):
        tok = tok.strip()
        if not tok:
            continue
        parts = tok.split("x")
        if len(parts) != 3 or not all(p.isdigit() and int(p) >= 1
                                      for p in parts):
            raise ValueError(f"bad partition {tok!r} (want e.g. 2x2x2)")
        out.append(tuple(int(p) for p in parts))
    if not out:
        raise ValueError("empty partition list")
    return out


def _parse_qsets(text: str) -> List[tuple]:
    """``f32,f32+f64`` -> [("float32",), ("float32", "float64")]."""
    names = {"f32": "float32", "f64": "float64", "float32": "float32",
             "float64": "float64"}
    out = []
    for group in text.split(","):
        group = group.strip()
        if not group:
            continue
        dts = []
        for tok in group.split("+"):
            tok = tok.strip()
            if tok not in names:
                raise ValueError(f"bad dtype {tok!r} (known: "
                                 f"{', '.join(sorted(set(names)))})")
            dts.append(names[tok])
        out.append(tuple(dts))
    if not out:
        raise ValueError("empty quantity list")
    return out


def changed_files(root: str) -> List[str]:
    """Python files touched vs HEAD (staged + unstaged) plus untracked —
    the pre-commit scope. Raises RuntimeError when git is unusable."""
    files = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            p = subprocess.run(args, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"{' '.join(args)}: {e}")
        if p.returncode != 0:
            raise RuntimeError(
                f"{' '.join(args)} failed: {p.stderr.strip()[:200]}")
        files.update(ln.strip() for ln in p.stdout.splitlines()
                     if ln.strip())
    return sorted(
        f for f in files
        if f.endswith(".py") and os.path.exists(os.path.join(root, f)))


def cmd_lint(args) -> int:
    from ..analysis import astlint

    root = args.root or REPO_ROOT
    if args.list_rules:
        for name in sorted(astlint.RULES):
            r = astlint.RULES[name]
            print(f"{name:24s} [{r.severity}] {r.doc}")
        return 0
    rules = ([t.strip() for t in args.rules.split(",") if t.strip()]
             if args.rules else None)
    if args.changed:
        try:
            paths = changed_files(root)
        except RuntimeError as e:
            print(f"[lint] --changed: {e}", file=sys.stderr)
            return 2
        if not paths:
            print("[lint] --changed: no changed Python files — "
                  "nothing to lint")
            return 0
    else:
        paths = args.paths or list(astlint.DEFAULT_PATHS)
    # expand once; lint_paths on the explicit file list is per-file
    # stats, not a second recursive walk
    files = astlint.iter_py_files(paths, root)
    try:
        findings, errors = astlint.lint_paths(files, repo_root=root,
                                              rules=rules)
    except ValueError as e:
        print(f"[lint] {e}", file=sys.stderr)
        return 2
    n_files = len(files)
    if n_files == 0:
        if args.changed:
            # an all-tests (or all-excluded) change set is a legitimately
            # empty input for the pre-commit hook, not a mistyped path
            print("[lint] --changed: every changed file is outside the "
                  "lint scope — nothing to lint")
            return 0
        print(f"[lint] no Python files under {paths!r} — nothing "
              "analyzed", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    try:
        baseline = astlint.load_baseline(baseline_path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"[lint] bad baseline {baseline_path}: {e}", file=sys.stderr)
        return 2
    new = [f for f in findings if f.fingerprint not in baseline]
    baselined = [f for f in findings if f.fingerprint in baseline]

    if args.write_baseline:
        astlint.write_baseline(baseline_path, findings)
        print(f"[lint] baseline rewritten: {len(findings)} fingerprint(s) "
              f"-> {baseline_path}")

    rec = _metrics(args, "lint_tool")
    if rec.enabled:
        rec.meta("analysis.lint", findings=len(findings), new=len(new),
                 baselined=len(baselined), files=n_files)

    if args.json:
        print(json.dumps({
            "kind": "lint-report", "files": n_files,
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined), "new": len(new),
            "errors": errors,
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        for e in errors:
            print(f"[lint] ERROR {e}", file=sys.stderr)
        print(f"[lint] {n_files} file(s): {len(new)} new finding(s), "
              f"{len(baselined)} baselined")
    if errors:
        # an unparseable file is an analysis failure, not a pass
        return 1
    return 1 if new else 0


def cmd_verify_plan(args) -> int:
    from ..analysis import verify_plan as vp

    try:
        methods = ([t.strip() for t in args.methods.split(",") if t.strip()]
                   if args.methods else None)
        configs = vp.sweep_configs(
            size=args.size, radius=args.radius,
            partitions=_parse_partitions(args.partitions),
            methods=methods, qsets=_parse_qsets(args.quantities))
    except ValueError as e:
        print(f"[verify-plan] {e}", file=sys.stderr)
        return 2
    rec = _metrics(args, "lint_tool")
    res = vp.run_sweep(configs,
                       perturb_collectives=args.perturb_collectives,
                       perturb_wire=args.perturb_wire,
                       perturb_dmas=args.perturb_dmas, rec=rec)
    if getattr(args, "placements", 0):
        pres = vp.run_placement_sweep(
            count=args.placements, size=args.size, radius=args.radius,
            partition=_parse_partitions(args.partitions)[0], rec=rec)
        res = {
            "verdicts": res["verdicts"] + pres["verdicts"],
            "checked": res["checked"] + pres["checked"],
            "failed": res["failed"] + pres["failed"],
            "skipped": res["skipped"] + pres["skipped"],
        }
    if getattr(args, "hierarchy", 0):
        hres = vp.run_hierarchy_sweep(
            hosts=args.hierarchy, size=args.size, radius=args.radius,
            partitions=_parse_partitions(args.partitions),
            perturb_dcn=getattr(args, "perturb_dcn", 0), rec=rec)
        res = {
            "verdicts": res["verdicts"] + hres["verdicts"],
            "checked": res["checked"] + hres["checked"],
            "failed": res["failed"] + hres["failed"],
            "skipped": res["skipped"] + hres["skipped"],
        }
    if getattr(args, "time", 0):
        calibration = None
        if getattr(args, "time_db", ""):
            import jax

            from ..plan import db as plandb

            db = plandb.load_db(args.time_db)
            row = plandb.lookup_calibration(
                db, jax.devices()[0].platform)
            if row is not None:
                calibration = row["calibration"]
        # the timed grid is deliberately small (first partition, one
        # f32 quantity, the base methods): it judges seconds, and
        # wall-clock per config is iters x a real exchange
        tconfigs = vp.sweep_configs(
            size=args.size, radius=args.radius,
            partitions=_parse_partitions(args.partitions)[:1],
            methods=methods, qsets=(("float32",),))
        tres = vp.run_time_sweep(tconfigs, iters=args.time,
                                 calibration=calibration,
                                 rel_tol=args.time_rel_tol,
                                 slow_s=args.time_slow, rec=rec)
        res = {
            "verdicts": res["verdicts"] + tres["verdicts"],
            "checked": res["checked"] + tres["checked"],
            "failed": res["failed"] + tres["failed"],
            "skipped": res["skipped"] + tres["skipped"],
        }
    verdicts = res["verdicts"]
    if args.json:
        print(json.dumps({
            "kind": "plan-sweep",
            "verdicts": [v.to_json() for v in verdicts],
            "checked": res["checked"], "failed": res["failed"],
            "skipped": res["skipped"],
        }, indent=1, sort_keys=True))
    else:
        for v in verdicts:
            if v.skipped:
                print(f"SKIP {v.label}: {v.reason}")
            elif v.ok:
                print(f"ok   {v.label}")
            else:
                bad = [c for c in v.checks if not c["ok"]]
                detail = "; ".join(
                    f"{c['name']} predicted {c['predicted']} != "
                    f"actual {c['actual']}" for c in bad) or v.reason
                print(f"FAIL {v.label}: {detail}")
        print(f"[verify-plan] {res['checked']} checked, "
              f"{res['failed']} failed, {res['skipped']} skipped")
    if res["checked"] == 0:
        print("[verify-plan] nothing analyzed: every sweep config was "
              "infeasible for this host (device count / radius "
              "constraints via plan/cost.feasible) — not a pass",
              file=sys.stderr)
        return 2
    return 1 if res["failed"] else 0


def cmd_jit_audit(args) -> int:
    from ..analysis import jit_audit as ja

    rec = _metrics(args, "lint_tool")
    try:
        r = ja.run_audit(size=args.size, iters=args.iters,
                         chunk=args.chunk, inject=args.inject or None,
                         rec=rec)
    except ValueError as e:
        print(f"[jit-audit] {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(r.to_json(), indent=1, sort_keys=True))
    else:
        verdict = "PASS" if r.ok else "FAIL"
        print(f"[jit-audit] {verdict}: {r.steps} step(s) in {r.chunks} "
              f"chunk(s); {r.recompiles} post-warmup recompile(s), "
              f"{len(r.transfer_trips)} transfer trip(s) "
              f"({r.warmup_compiles} warmup compiles)")
        for t in r.transfer_trips:
            print(f"  transfer: {t}")
    return 0 if r.ok else 1


def cmd_all(args) -> int:
    rcs = {}
    print("== lint ==")
    rcs["lint"] = cmd_lint(args)
    print("== verify-plan ==")
    rcs["verify-plan"] = cmd_verify_plan(args)
    print("== jit-audit ==")
    rcs["jit-audit"] = cmd_jit_audit(args)
    print("[all] " + "  ".join(f"{k}: rc={v}" for k, v in rcs.items()))
    if any(rc == 1 for rc in rcs.values()):
        return 1
    if any(rc == 2 for rc in rcs.values()):
        return 2
    return 0


def _metrics(args, app: str):
    from ._bench_common import start_metrics

    return start_metrics(args, app)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="lint_tool",
        description="static analysis: repo lint, plan/HLO conformance, "
                    "jit recompile/host-sync audit")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, backend=False):
        sp.add_argument("--json", action="store_true",
                        help="machine-readable output")
        sp.add_argument(
            "--metrics-out",
            default=os.environ.get("STENCIL_METRICS_OUT", ""),
            help="append analysis.* telemetry records here (schema "
                 "obs/telemetry.py; report --validate gates them)")
        sp.add_argument("--run-id", default="")
        if backend:
            sp.add_argument("--cpu", type=int, default=0,
                            help="force N virtual CPU devices")

    def lint_flags(sp):
        sp.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the repo's "
                             "library + scripts set)")
        sp.add_argument("--changed", action="store_true",
                        help="lint only `git diff --name-only` files "
                             "(+ untracked) — the pre-commit path")
        sp.add_argument("--baseline", default="",
                        help=f"fingerprint baseline file (default "
                             f"{DEFAULT_BASELINE} at the repo root)")
        sp.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline with the current "
                             "findings (atomic)")
        sp.add_argument("--rules", default="",
                        help="comma-separated rule subset")
        sp.add_argument("--list-rules", action="store_true")
        sp.add_argument("--root", default="",
                        help="repo root (default: autodetected)")

    def plan_flags(sp):
        sp.add_argument("--size", type=int, default=16)
        sp.add_argument("--radius", type=int, default=2)
        sp.add_argument("--partitions", default="2x2x2,1x2x4")
        sp.add_argument("--methods", default="",
                        help="comma-separated method subset (default: "
                             "all four)")
        sp.add_argument("--quantities", default="f32,f32+f32+f32,"
                                                "f32+f32+f64",
                        help="comma-separated quantity groups, dtypes "
                             "joined by + (e.g. f32,f32+f64)")
        sp.add_argument("--perturb-collectives", type=int, default=0,
                        help="offset the IR's collective prediction "
                             "(the auditor must TRIP — CI's proof knob)")
        sp.add_argument("--perturb-wire", type=int, default=0)
        sp.add_argument("--perturb-dmas", type=int, default=0)
        sp.add_argument("--hierarchy", type=int, default=0,
                        help="ALSO audit the hierarchical (ICI+DCN) "
                             "lowering on an N-virtual-host fabric: "
                             "predicted DCN transfers/bytes vs the "
                             "executed schedule, inner census pins "
                             "unchanged, bit parity with the flat plan "
                             "(the ISSUE-17 DCN gate; 0 = off)")
        sp.add_argument("--perturb-dcn", type=int, default=0,
                        help="offset the DCN transfer prediction (the "
                             "hierarchy auditor must TRIP)")
        sp.add_argument("--placements", type=int, default=0,
                        help="ALSO audit N non-identity block placements "
                             "on the first partition: mesh device order "
                             "== the permuted assignment, compiled "
                             "source_target_pairs == the plan's logical "
                             "schedule, results bit-identical to "
                             "identity (the ISSUE-15 placement gate)")
        sp.add_argument("--time", type=int, default=0,
                        help="ALSO time N exchange iterations per method "
                             "on the first partition (single-f32 grid) "
                             "and judge the cost model's predicted "
                             "seconds against the measured trimean±MAD "
                             "band — the calibration drift sentinel "
                             "(the ISSUE-18 timed gate; 0 = off)")
        sp.add_argument("--time-db", default="",
                        help="plan DB whose installed fitted calibration "
                             "prices the --time predictions (default: "
                             "the modeled DEFAULT_CALIBRATION)")
        sp.add_argument("--time-rel-tol", type=float, default=0.75,
                        help="--time band floor as a fraction of the "
                             "measured trimean (default 0.75 — wide: a "
                             "few in-process samples judge multiple-x "
                             "staleness, not 5%% drift; keep it < 1 or "
                             "an under-prediction can never trip)")
        sp.add_argument("--time-slow", type=float, default=0.0,
                        help="sleep this many seconds inside one timed "
                             "iteration (the --time auditor must TRIP — "
                             "CI's proof knob, like --perturb-*)")

    def audit_flags(sp):
        sp.add_argument("--size", type=int, default=16)
        sp.add_argument("--iters", type=int, default=10)
        sp.add_argument("--chunk", type=int, default=4)
        sp.add_argument("--inject", default="",
                        choices=["", "recompile", "host-sync"],
                        help="deliberately-bad fixtures: skip warming "
                             "the tail chunk size / pull a scalar "
                             "inside the guard — the audit must FAIL")

    sp = sub.add_parser("lint", help="AST lint of the repo contracts")
    lint_flags(sp)
    common(sp)

    sp = sub.add_parser("verify-plan",
                        help="ExchangePlan IR vs compiled-HLO census")
    plan_flags(sp)
    common(sp, backend=True)

    sp = sub.add_parser("jit-audit",
                        help="recompile/host-sync audit of the step loop")
    audit_flags(sp)
    common(sp, backend=True)

    sp = sub.add_parser("all", help="the full static suite (CI gate)")
    lint_flags(sp)
    plan_flags(sp)
    # jit-audit's --size collides with verify-plan's; `all` shares one
    # --size (16 suits both) and dedicated iters/chunk/inject knobs
    sp.add_argument("--iters", type=int, default=10)
    sp.add_argument("--chunk", type=int, default=4)
    sp.add_argument("--inject", default="")
    common(sp, backend=True)

    args = p.parse_args(argv)

    if getattr(args, "cpu", 0):
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)

    if args.cmd == "lint":
        return cmd_lint(args)
    if args.cmd == "verify-plan":
        return cmd_verify_plan(args)
    if args.cmd == "jit-audit":
        return cmd_jit_audit(args)
    if args.cmd == "all":
        return cmd_all(args)
    raise AssertionError(args.cmd)


if __name__ == "__main__":
    raise SystemExit(main())
