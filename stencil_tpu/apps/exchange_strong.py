"""exchange-strong — pure halo-exchange benchmark, fixed total domain.

TPU-native port of the reference benchmark (reference:
bin/exchange_strong.cu): same measurement and CSV row as exchange-weak but
without weak scaling, for strong-scaling curves.

Usage: python -m stencil_tpu.apps.exchange_strong 512 512 512 30 [--naive|--random]
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax

from ..parallel import Method
from ..utils import logging as log
from . import exchange_weak


def run(x, y, z, iters=30, **kw) -> dict:
    return exchange_weak.run(x, y, z, iters=iters, weak=False, **kw)


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="strong-scaled halo exchange benchmark")
    p.add_argument("x", type=int)
    p.add_argument("y", type=int)
    p.add_argument("z", type=int)
    p.add_argument("iters", type=int)
    p.add_argument("--prefix", default="")
    p.add_argument("--naive", action="store_true")
    p.add_argument("--random", action="store_true")
    p.add_argument("--direct26", action="store_true")
    p.add_argument("--cpu", type=int, default=0)
    from ._bench_common import add_metrics_flags, start_metrics
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    start_metrics(args, "exchange_strong")
    r = run(
        args.x,
        args.y,
        args.z,
        iters=args.iters,
        naive=args.naive,
        random_=args.random,
        method=Method.DIRECT26 if args.direct26 else Method.AXIS_COMPOSED,
        prefix=args.prefix,
    )
    print(exchange_weak.csv_row(r))
    log.info(f"exchange {r['gb_per_s']:.2f} GB/s logical halo bytes")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
