"""astaroth — the MHD mini-app driver, weak-scaled.

TPU-native port of the reference driver (reference: astaroth/astaroth.cu):
8 double-precision fields, radius-3 halos, per iteration 3 RK3 substeps of
{interior integrate / halo exchange / exterior integrate}, buffers swapped
per iteration, dt = 1e-8. Init: hash-random everything, constant 0.5
lnrho, radial-explosion velocity (astaroth.cu:493-520). Output row matches
the reference (astaroth.cu:672-679):

  <processes>,<nx>,<ny>,<nz>,<iter trimean s>,<exch trimean s>

(nx/ny/nz are the per-config base extents; the global domain is that times
decompose_zyx(#devices), astaroth.cu:263-276,370-377.)

Usage: python -m stencil_tpu.apps.astaroth 10 [--conf path] [--cpu 8]
"""

from __future__ import annotations

import argparse
import os
import time
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..api import DistributedDomain
from ..astaroth.config import load_config
from ..astaroth.init import const_init, hash_init, radial_explosion_init
from ..astaroth.integrate import FIELDS, make_astaroth_step, uses_pallas
from ..astaroth.reductions import Reductions
from ..geometry import Dim3, Radius, prime_factors
from ..obs import telemetry
from ..parallel import Method
from ..apps._bench_common import placement_from_flags
from ..utils import timer
from ..utils.statistics import Statistics
from ..utils.sync import hard_sync
from ..utils import logging as log

DEFAULT_CONF = os.path.join(os.path.dirname(__file__), "..", "astaroth", "astaroth.conf")


def decompose_zyx(p: int) -> Dim3:
    """Split device count over axes, z first (reference: astaroth.cu:263-276)."""
    x = y = z = 1
    for pf in prime_factors(p):
        if z <= y and z <= x:
            z *= pf
        elif y <= x:
            y *= pf
        else:
            x *= pf
    return Dim3(x, y, z)


def run(
    iters: int = 10,
    conf: str = DEFAULT_CONF,
    devices=None,
    overlap: bool = True,
    method: Method = Method.AXIS_COMPOSED,
    trivial: bool = False,
    random_: bool = False,
    no_compute: bool = False,
    dtype: str = "float64",
    nx: Optional[int] = None,
    paraview_init: bool = False,
    paraview_final: bool = False,
    swap_per_substep: bool = False,
    reductions: bool = False,
    dt: float = 1e-8,
    use_pallas=None,
    chunk: int = 1,
    kernel_variant: Optional[str] = None,
    metrics_dma: bool = False,
    ckpt_dir: Optional[str] = None,
    ckpt_every: int = 0,
    ckpt_keep: int = 3,
    resume: bool = False,
    batch_quantities: bool = True,
    autotune: bool = False,
    plan_db: Optional[str] = None,
    health_every: int = 0,
    max_abs: Optional[float] = None,
    max_rollbacks: int = 3,
    rollback_backoff: float = 0.25,
    inject: Optional[str] = None,
) -> dict:
    """Run ``iters`` iterations (plus one untimed warmup chunk) and return
    timing stats + the domain.

    Iterations execute in fused chunks of ``chunk`` compiled together; when
    ``chunk`` does not divide ``iters``, the count is rounded UP to the next
    chunk multiple (a tail program would double the compile cost for a
    benchmark driver) — the returned ``iters_run`` records the actual
    number of timed iterations the state advanced."""
    devices = list(devices) if devices is not None else jax.devices()
    if (overlap and np.dtype(dtype) == np.float64
            and all(d.platform == "tpu" for d in devices)
            and os.environ.get("STENCIL_F64_OVERLAP") != "1"):
        # fp64 on TPU: the serialized step compiles in ~2 min. The round-3
        # per-substep overlap structure (7 integrate regions x 3 substeps
        # x f64 emulation expansion) blew a 25-minute compile budget; the
        # round-4 hoisted-exchange overlap iteration is 9 bodies and is
        # expected to compile — set STENCIL_F64_OVERLAP=1 to take it
        # (default stays serialized until the chip record lands,
        # BASELINE.md round 4, scripts/probe_f64*.py)
        log.info("fp64 on TPU: forcing overlap=False (set "
                 "STENCIL_F64_OVERLAP=1 for the hoisted overlap structure)")
        overlap = False
    info, ok = load_config(conf)
    if not ok:
        log.warn(f"config has uninitialized values: {info.uninitialized()[:5]} ...")
    if nx is not None:
        info.int_params["AC_nx"] = nx
        info.int_params["AC_ny"] = nx
        info.int_params["AC_nz"] = nx
        info.update_builtin_params()

    # weak scaling: base extent x device decomposition. On TPU the split
    # stays in z/y (geometry.decompose_zy): every chip keeps the tight-x
    # layout, no minor-dim slab slicing, 2D ICI mesh — the reference's
    # 3-axis decompose_zyx (astaroth.cu:263-276) remains for CPU.
    if len(devices) > 1 and all(d.platform == "tpu" for d in devices):
        from ..geometry import decompose_zy

        d3 = decompose_zy(len(devices))
    else:
        d3 = decompose_zyx(len(devices))
    size = Dim3(
        info.int_params["AC_nx"] * d3.x,
        info.int_params["AC_ny"] * d3.y,
        info.int_params["AC_nz"] * d3.z,
    )

    dd = DistributedDomain(size.x, size.y, size.z)
    radius = Radius.constant(3)
    if d3.x == 1 and use_pallas is not False:
        # tight-x layout on a single-block x axis (any y/z mesh): no x halo
        # columns (kernel forms the periodic x pencils with lane rolls) —
        # sheds the px/nx DMA lane padding AND the x self-fill's lane-tile
        # RMW entirely; multi-block y/z halos ride the exchange and their
        # overlap shells take the x-wrapped slab integrate. Engage only
        # when the fused kernel supports the resulting layout.
        from ..domain.grid import GridSpec
        from ..ops.pallas_astaroth import substep_supported

        tight = radius.without_x()
        tight_spec = GridSpec(size, d3, tight)
        if (np.dtype(dtype) == np.float32
                and all(d.platform == "tpu" for d in devices)
                and substep_supported(tight_spec, jnp.float32)):
            radius = tight
    dd.set_radius(radius)
    dd.set_methods(method)
    # the 8-field state is where quantity batching pays: one packed
    # ppermute carrier per axis phase instead of 8 (default on; the A/B
    # knob keeps the per-quantity collectives measurable)
    dd.set_quantity_batching(batch_quantities)
    dd.set_devices(devices)
    dd.set_placement(placement_from_flags(trivial, random_))
    if autotune:
        # plan/ subsystem: the 8-field exchange is where plan choice pays
        # (batched vs per-quantity, partition shape); DB hits replay with
        # zero probes, misses probe the statically-ranked top candidates
        dd.enable_autotune(db_path=plan_db)
    handles = {name: dd.add_data(name, dtype) for name in FIELDS}
    dd.realize()
    if autotune:
        method = dd._method  # the tuned method labels the CSV row

    # init (reference: astaroth.cu:493-520): hash-random everything,
    # constant 0.5 lnrho, radial-explosion velocity
    rec = telemetry.get()
    np_dtype = np.dtype(dtype)
    with rec.span("astaroth.init", phase="init"):
        ds = (
            info.real_params["AC_dsx"],
            info.real_params["AC_dsy"],
            info.real_params["AC_dsz"],
        )
        h = hash_init(size, dtype=np_dtype)  # coordinate-determined, same per field
        for name in ("entropy", "ax", "ay", "az"):
            dd.set_curr_global(handles[name], h)
        dd.set_curr_global(handles["lnrho"], const_init(size, 0.5, dtype=np_dtype))
        uux, uuy, uuz = radial_explosion_init(size, ds=ds, dtype=np_dtype)
        dd.set_curr_global(handles["uux"], uux)
        dd.set_curr_global(handles["uuy"], uuy)
        dd.set_curr_global(handles["uuz"], uuz)

    if paraview_init:
        dd.write_paraview("init")

    # checkpoint/restart (ckpt/): the 8 fields' per-block interiors are the
    # durable campaign state; resume elastically replaces the fresh init
    start = 0
    if ckpt_dir and no_compute:
        log.warn("--ckpt-dir ignored with --no-compute (pure-exchange "
                 "benchmark has no campaign state worth resuming)")
        ckpt_dir = None
    if ckpt_dir and resume:
        from ._bench_common import resume_from_checkpoint

        start = resume_from_checkpoint(dd, ckpt_dir, iters)

    def save_ckpt(step: int, state) -> None:
        for name in FIELDS:
            dd.set_curr(handles[name], state[name])
        dd.save_checkpoint(ckpt_dir, step, keep=ckpt_keep)

    curr = {name: dd.get_curr(handles[name]) for name in FIELDS}
    nxt = {name: dd.get_next(handles[name]) for name in FIELDS}

    iter_time = Statistics()
    exch_time = Statistics()
    if no_compute:
        # measure pure exchange per substep (reference --no-compute flag)
        loop = dd.halo_exchange.make_loop(3)
        with rec.span("astaroth.warmup", phase="compile"):
            curr = loop(curr)
            hard_sync(curr)
        for _ in range(iters):
            t0 = time.perf_counter()
            curr = loop(curr)
            hard_sync(curr)
            dt_iter = time.perf_counter() - t0
            iter_time.insert(dt_iter)
            exch_time.insert(dt_iter)
            rec.emit("span", "astaroth.exchange", phase="exchange",
                     seconds=dt_iter, iters=3)
    else:
        chunk = max(1, min(chunk, iters))
        step = make_astaroth_step(
            dd.halo_exchange,
            info,
            dt=dt,
            overlap=overlap,
            swap_per_substep=swap_per_substep,
            use_pallas=use_pallas,
            dtype=dtype,
            iters=chunk,
            kernel_variant=kernel_variant,
        )
        with rec.span("astaroth.warmup", phase="compile", iters=chunk):
            if ckpt_dir:
                # step-exact contract for checkpointed runs: warm the
                # compile caches on throwaway copies (the step donates its
                # inputs), never advancing the real state
                step(jax.tree.map(lambda a: a + 0, curr),
                     jax.tree.map(lambda a: a + 0, nxt))
                hard_sync(curr)
            else:
                curr, nxt = step(curr, nxt)  # compile + warm (one chunk)
                hard_sync(curr)
        # The exchange share can't be timed inside the fused step, so it is
        # measured as a standalone loop on the same state each iteration
        # (halo exchange is idempotent on exchanged data, so this does not
        # perturb the fields) — the analogue of the reference's exchElapsed
        # within the iteration (astaroth.cu:586-590). The loop length
        # mirrors the step's exchanges per iteration: 3 (one per substep)
        # on the XLA path, 1 on the fused Pallas path (non-swap mode).
        pallas_on = uses_pallas(dd.halo_exchange, use_pallas, dtype)
        n_ex = 1 if (pallas_on and not swap_per_substep) else 3
        exch_loop = dd.halo_exchange.make_loop(n_ex)
        curr = exch_loop(curr)
        hard_sync(curr)

        # Self-healing (fault/): when a health guard or injection schedule
        # is configured, the 8-field loop runs under the same guarded
        # engine as jacobi3d (step -> inject -> check -> checkpoint, with
        # rollback-with-backoff on a NumericalFault); otherwise the
        # historical fixed-chunk loop runs untouched — identical compiled
        # programs either way.
        from ..fault import (FaultPlan, HealthGuard, RecoveryPolicy,
                             chunk_plan, run_guarded)

        guard = (HealthGuard(every=health_every, max_abs=max_abs)
                 if health_every > 0 else None)
        injector = FaultPlan.from_spec(inject)
        done = start
        if guard is not None or injector is not None:
            steps_cache = {chunk: step}

            def get_step(k: int):
                # fault-mode chunk plans may carry tail sizes the fixed
                # benchmark chunking never needed; compile them on demand
                if k not in steps_cache:
                    steps_cache[k] = make_astaroth_step(
                        dd.halo_exchange, info, dt=dt, overlap=overlap,
                        swap_per_substep=swap_per_substep,
                        use_pallas=use_pallas, dtype=dtype, iters=k,
                        kernel_variant=kernel_variant,
                    )
                return steps_cache[k]

            def plan_fn(s: int):
                return chunk_plan(
                    s, iters, chunk,
                    every=(ckpt_every if (ckpt_dir and ckpt_every > 0) else 0,
                           health_every if guard is not None else 0),
                    at=injector.steps() if injector is not None else (),
                )

            def step_fn(st, k):
                nonlocal nxt
                c, n2 = get_step(k)(st, nxt)
                hard_sync(c)
                nxt = n2
                return c

            def on_chunk(st, k, per, done_now):
                for _ in range(k):
                    iter_time.insert(per)
                rec.emit("span", "astaroth.iter", phase="step", seconds=per,
                         iters=k)
                t1 = time.perf_counter()
                st = exch_loop(st)
                hard_sync(st)
                ex_dt = time.perf_counter() - t1
                exch_time.insert(ex_dt)
                rec.emit("span", "astaroth.exchange", phase="exchange",
                         seconds=ex_dt, iters=n_ex)
                return st

            save_fn = restore_fn = quarantine_fn = flush_fn = None
            if ckpt_dir:
                if ckpt_every > 0:
                    save_fn = save_ckpt
                flush_fn = dd.flush_checkpoints

                def restore_fn():
                    s = dd.restore_checkpoint(ckpt_dir)
                    if s is None:
                        return None
                    return s, {name: dd.get_curr(handles[name])
                               for name in FIELDS}

                def quarantine_fn(s):
                    from ..ckpt import quarantine_snapshot, snapshot_name

                    quarantine_snapshot(
                        ckpt_dir, snapshot_name(s),
                        reason="restored state failed health check")

            curr, done = run_guarded(
                curr, start=start, iters=iters, plan_fn=plan_fn,
                step_fn=step_fn, guard=guard, injector=injector,
                policy=RecoveryPolicy(max_rollbacks=max_rollbacks,
                                      backoff_s=rollback_backoff),
                save_fn=save_fn, ckpt_every=ckpt_every,
                restore_fn=restore_fn, quarantine_fn=quarantine_fn,
                flush_fn=flush_fn, on_chunk=on_chunk, spec=dd.spec,
                ckpt_dir=ckpt_dir, app="astaroth",
            )
        else:
            next_ckpt = (start // ckpt_every + 1) * ckpt_every if (
                ckpt_dir and ckpt_every > 0) else None
            while done < iters:
                t0 = time.perf_counter()
                curr, nxt = step(curr, nxt)
                hard_sync(curr)
                per = (time.perf_counter() - t0) / chunk
                for _ in range(chunk):
                    iter_time.insert(per)
                rec.emit("span", "astaroth.iter", phase="step", seconds=per,
                         iters=chunk)
                done += chunk
                if next_ckpt is not None and done >= next_ckpt and done < iters:
                    save_ckpt(done, curr)
                    next_ckpt = (done // ckpt_every + 1) * ckpt_every
                t0 = time.perf_counter()
                curr = exch_loop(curr)
                hard_sync(curr)
                ex_dt = time.perf_counter() - t0
                exch_time.insert(ex_dt)
                rec.emit("span", "astaroth.exchange", phase="exchange",
                         seconds=ex_dt, iters=n_ex)
        if ckpt_dir:
            if done > start or start == 0:
                save_ckpt(done, curr)  # the final state is always durable
            # a resume that found nothing left to run never re-labels the
            # existing (possibly further-along) snapshot
            dd.finish_checkpoints()

    timed_iters = iter_time.count()
    if iter_time.count() == 0:
        # resumed at/past the target iteration count: nothing left to time
        # (inf placeholder; non-finite gauges are skipped — they would
        # serialize as non-strict JSON)
        log.info(f"resume found step {start} >= iters {iters}; no timed work")
        iter_time.insert(float("inf"))
    if exch_time.count() == 0:
        exch_time.insert(float("inf"))

    if rec.enabled:
        # compile-time truth of this method's exchange (on-wire volume)
        telemetry.record_exchange_truth(
            dd.halo_exchange, dict(curr), [np_dtype.itemsize] * len(FIELDS))
        if metrics_dma and not no_compute:
            if uses_pallas(dd.halo_exchange, use_pallas, dtype):
                telemetry.record_dma_traffic(
                    lambda: (
                        make_astaroth_step(
                            dd.halo_exchange, info, dt=dt, overlap=overlap,
                            swap_per_substep=swap_per_substep,
                            use_pallas=use_pallas, dtype=dtype, iters=chunk,
                            kernel_variant=kernel_variant,
                        ),
                        (curr, nxt),
                    ),
                )
            else:
                rec.meta("dma.skipped",
                         reason="pallas fused substep not engaged")
        if np.isfinite(iter_time.trimean()):
            rec.gauge("astaroth.iter_trimean_s", iter_time.trimean(),
                      phase="step", unit="s")
        if np.isfinite(exch_time.trimean()):
            rec.gauge("astaroth.exch_trimean_s", exch_time.trimean(),
                      phase="exchange", unit="s")

    for name in FIELDS:
        dd.set_curr(handles[name], curr[name])
        if not no_compute:
            dd.set_next(handles[name], nxt[name])

    if paraview_final:
        dd.write_paraview("final")

    result = {
        "processes": jax.process_count(),
        "devices": len(devices),
        "nx": info.int_params["AC_nx"],
        "ny": info.int_params["AC_ny"],
        "nz": info.int_params["AC_nz"],
        "global": size,
        "iter_trimean_s": iter_time.trimean(),
        "exch_trimean_s": exch_time.trimean(),
        "iters_run": timed_iters,
        "domain": dd,
        "handles": handles,
        "info": info,
    }
    if reductions:
        red = Reductions(dd.halo_exchange)
        result["reductions"] = {
            "lnrho": red.scal(dd.get_curr(handles["lnrho"])),
            "uu": red.vec(
                dd.get_curr(handles["uux"]),
                dd.get_curr(handles["uuy"]),
                dd.get_curr(handles["uuz"]),
            ),
        }
    return result


def csv_row(r: dict) -> str:
    return (
        f"{r['devices']},{r['nx']},{r['ny']},{r['nz']},"
        f"{r['iter_trimean_s']:e},{r['exch_trimean_s']:e}"
    )


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="Astaroth MHD mini-app (TPU)")
    p.add_argument("iters", type=int, nargs="?", default=10)
    p.add_argument("--conf", default=DEFAULT_CONF)
    p.add_argument("--nx", type=int, default=None, help="override AC_n{x,y,z}")
    p.add_argument("--trivial", action="store_true", help="trivial placement")
    p.add_argument("--random", action="store_true", help="random placement")
    p.add_argument("--no-compute", action="store_true")
    p.add_argument("--no-overlap", action="store_true")
    p.add_argument("--paraview-init", action="store_true")
    p.add_argument("--paraview-final", action="store_true")
    p.add_argument("--f32", action="store_true", help="float32 fields (TPU-native)")
    p.add_argument("--f64", action="store_true",
                   help="float64 fields on TPU (software-emulated: works on "
                        "the serialized XLA path, ~45 ms/iter at 64^3 with "
                        "a ~2 min compile; the reference's native dtype)")
    p.add_argument("--reductions", action="store_true", help="print field reductions")
    p.add_argument("--no-pallas", action="store_true",
                   help="force the unfused XLA substep path")
    p.add_argument("--per-quantity-exchange", action="store_true",
                   help="disable quantity batching: one collective per "
                        "field per phase instead of one packed carrier for "
                        "all 8 fields (the A/B baseline)")
    p.add_argument("--kernel-variant", choices=("shift", "ring"), default=None,
                   help="fused-substep sliding-window discipline: 'shift' "
                        "(plane-copy window shifts, the recorded kernel) or "
                        "'ring' (shift-free modular-slot rotation); default "
                        "reads STENCIL_ASTAROTH_VARIANT, else 'shift'")
    p.add_argument("--chunk", type=int, default=1,
                   help="iterations fused per dispatch (benchmarking; a "
                        "final partial chunk still runs a full chunk)")
    p.add_argument("--ckpt-dir", type=str, default="",
                   help="write elastic checkpoint snapshots here (ckpt/ "
                        "subsystem: sharded npz + manifest, crash-safe)")
    p.add_argument("--ckpt-every", type=int, default=0,
                   help="checkpoint every N iterations (0 = only the final "
                        "state; needs --ckpt-dir)")
    p.add_argument("--ckpt-keep", type=int, default=3,
                   help="retention: keep the newest N snapshots")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest valid snapshot under "
                        "--ckpt-dir when one exists (fresh start otherwise)")
    p.add_argument("--health-every", type=int, default=0,
                   help="numerical health guard (fault/): one fused "
                        "isfinite reduction over all 8 fields every N "
                        "steps; a fault rolls back to the newest valid "
                        "snapshot (0 = off)")
    p.add_argument("--max-abs", type=float, default=0.0,
                   help="with --health-every, divergence ceiling on any "
                        "field's max|u| (0 = no ceiling)")
    p.add_argument("--max-rollbacks", type=int, default=3,
                   help="rollbacks allowed per faulting step before the "
                        "run aborts with rc 43 + an evidence bundle")
    p.add_argument("--rollback-backoff", type=float, default=0.25,
                   help="first-retry backoff seconds (doubles per repeat)")
    p.add_argument("--inject", type=str, default="",
                   help="deterministic fault injection spec (see "
                        "fault/inject.py; default: STENCIL_FAULT_INJECT)")
    p.add_argument("--autotune", action="store_true",
                   help="choose the exchange plan (partition x method x "
                        "quantity batching) via the plan/ autotuner; a plan-"
                        "DB hit replays with zero probes")
    p.add_argument("--plan-db", type=str, default="",
                   help="on-disk plan DB (JSON) for --autotune")
    p.add_argument("--cpu", type=int, default=0)
    from ._bench_common import add_metrics_flags, start_metrics
    add_metrics_flags(p, dma=True)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    rec = start_metrics(args, "astaroth")
    # dtype default: the reference's double on CPU, float32 on TPU (f64 is
    # software-emulated on TPU; it works through the serialized XLA path —
    # run() forces overlap off there — but is ~20x slower than fp32)
    use_f64 = args.f64 or (
        not args.f32 and jax.devices()[0].platform != "tpu"
    )
    if use_f64:
        jax.config.update("jax_enable_x64", True)
    elif not args.f32 and not args.f64:
        log.info("TPU platform: defaulting to float32 fields (use --f64 to force)")
    from ..fault import FAULT_RC, RecoveryExhausted

    try:
        r = run(
            iters=args.iters,
            conf=args.conf,
            trivial=args.trivial,
            random_=args.random,
            no_compute=args.no_compute,
            overlap=not args.no_overlap,
            dtype="float64" if use_f64 else "float32",
            nx=args.nx,
            paraview_init=args.paraview_init,
            paraview_final=args.paraview_final,
            reductions=args.reductions,
            use_pallas=False if args.no_pallas else None,
            chunk=args.chunk,
            kernel_variant=args.kernel_variant,
            metrics_dma=args.metrics_dma and rec.enabled,
            ckpt_dir=args.ckpt_dir or None,
            ckpt_every=args.ckpt_every,
            ckpt_keep=args.ckpt_keep,
            resume=args.resume,
            batch_quantities=not args.per_quantity_exchange,
            autotune=args.autotune,
            plan_db=args.plan_db or None,
            health_every=args.health_every,
            max_abs=args.max_abs or None,
            max_rollbacks=args.max_rollbacks,
            rollback_backoff=args.rollback_backoff,
            inject=args.inject or None,
        )
    except RecoveryExhausted as e:
        log.error(f"astaroth: {e}")
        if rec.enabled:
            rec.record_timer_buckets()
            rec.close()
        return FAULT_RC
    print(csv_row(r))
    log.info(timer.report())
    if rec.enabled:
        rec.record_timer_buckets()
        rec.close()
    if "reductions" in r:
        for k, v in r["reductions"].items():
            log.info(f"{k}: {v}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
