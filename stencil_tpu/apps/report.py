"""report — aggregate telemetry metrics JSONL into trimean tables.

Consumes the one-JSON-object-per-line files the bench apps write via
``--metrics-out`` (schema: stencil_tpu/obs/telemetry.py), across any
number of files/processes/runs, and reports:

- spans: per-name count / min / trimean / max seconds
  (``utils/statistics.Statistics`` — the reference's canonical trimean,
  bin/statistics.hpp:17);
- counters: the static byte/count truth (collective census, DMA bytes,
  logical/moved exchange bytes) with cross-record consistency flagged;
- gauges: per-name trimean (throughputs, timer buckets);
- an optional vs-baseline delta against a JSON file of recorded numbers
  (BASELINE.json / a bench.py payload / any flat {name: number} map).

``--validate`` makes it the CI schema gate: every line must parse and
satisfy the telemetry schema, or the exit code is 1 (``--ledger`` extends
the same gate to a performance-ledger file, ``obs/ledger.py`` schema).
``--trace-out`` exports the records as a Chrome-trace/Perfetto timeline
(``obs/trace_export.py``); ``--follow`` re-reads growing metrics files
and re-renders the tables in place — a run-status view for long hardware
sessions (add ``--heartbeat`` or set ``STENCIL_HEARTBEAT_FILE`` to also
show watchdog heartbeat freshness).

Usage:
  python -m stencil_tpu.apps.report m1.jsonl [m2.jsonl ...] [--markdown]
  python -m stencil_tpu.apps.report metrics.jsonl --validate
  python -m stencil_tpu.apps.report metrics.jsonl --baseline BASELINE.json
  python -m stencil_tpu.apps.report metrics.jsonl --trace-out trace.json
  python -m stencil_tpu.apps.report metrics.jsonl --follow
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..obs import telemetry
from ..obs.watchdog import HEARTBEAT_FILE_ENV
from ..utils.statistics import Statistics


def load(paths: List[str]) -> Tuple[List[dict], List[str]]:
    """Read + schema-validate records from JSONL files.

    Returns (valid records, error strings); invalid lines are reported,
    not silently dropped into the aggregate.
    """
    records: List[dict] = []
    errors: List[str] = []
    for path in paths:
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    errors.append(f"{path}:{i}: unparseable JSON ({e})")
                    continue
                errs = telemetry.validate_record(rec)
                if errs:
                    errors.extend(f"{path}:{i}: {e}" for e in errs)
                else:
                    records.append(rec)
    return records, errors


def _agg_key(rec: dict) -> str:
    """Aggregation key: the record name, split per exchange method when a
    ``method`` tag is present — a method-ablation run intentionally emits
    different census/byte/timing values per method, and folding them under
    one name would mix timings and false-positive the DISAGREE flag. The
    ``batched`` tag splits the same way: a quantity-batching A/B run emits
    both legs' truths (e.g. ``exchange.permutes_per_quantity`` 6/Q vs 6),
    and averaging them would read as neither. ``mode`` is the campaign
    A/B's tag (``campaign.step_latency_s`` carries batched AND sequential
    samples in one ab run — a folded p99 would describe neither leg)."""
    # ``wire`` splits the bf16/fp8-on-the-wire A/B (bench_exchange
    # --wire-ab): the compressed and native legs' timings/census differ
    # by design. ``variant`` splits the kernel-variant legs the same way
    # (the fused compute+exchange A/B: a fused.overlap_fraction or
    # exchange.trimean_s folded across variants would describe neither).
    # ``priority`` splits the serving daemon's per-class latency gauges
    # (serve.p99_ms): a folded p99 would average high and low lanes into
    # a number that describes neither class's SLO
    name = rec["name"]
    tags = [str(rec[t])
            for t in ("method", "batched", "mode", "wire", "variant",
                      "priority")
            if t in rec]
    if tags:
        return f"{name}[{','.join(tags)}]"
    return name


def aggregate(records: List[dict]) -> dict:
    """Fold records into per-name statistics (per-method names when
    tagged, see :func:`_agg_key`).

    Spans and gauges aggregate across processes AND runs (each sample
    keeps equal weight — the reference trimean discipline). Counters are
    static truths PER CONFIGURATION — one key can legitimately carry
    several distinct values (a radius sweep in one run, multiple runs
    appended to one file), so the table shows the distinct set as a range
    rather than presuming agreement.
    """
    spans: Dict[str, Statistics] = {}
    span_phase: Dict[str, str] = {}
    gauges: Dict[str, Statistics] = {}
    counters: Dict[str, dict] = {}
    runs, procs, apps = set(), set(), set()
    for rec in records:
        runs.add(rec["run"])
        procs.add(rec["proc"])
        if "app" in rec:
            apps.add(rec["app"])
        kind, name = rec["kind"], _agg_key(rec)
        if kind == "span":
            spans.setdefault(name, Statistics()).insert(rec["seconds"])
            if "phase" in rec:
                span_phase[name] = rec["phase"]
        elif kind == "gauge":
            gauges.setdefault(name, Statistics()).insert(rec["value"])
        elif kind == "counter":
            c = counters.setdefault(
                name, {"n": 0, "value": set(), "bytes": set()}
            )
            c["n"] += 1
            if "value" in rec:
                c["value"].add(rec["value"])
            if "bytes" in rec:
                c["bytes"].add(rec["bytes"])
    return {
        "spans": spans,
        "span_phase": span_phase,
        "gauges": gauges,
        "counters": counters,
        "runs": sorted(runs),
        "procs": sorted(procs),
        "apps": sorted(apps),
        "n_records": len(records),
    }


def _fmt_set(s: set) -> str:
    if not s:
        return "-"
    if len(s) == 1:
        return str(next(iter(s)))
    return f"{min(s)}..{max(s)} ({len(s)} distinct)"


def _rows_to_table(header: List[str], rows: List[List[str]],
                   markdown: bool) -> List[str]:
    if markdown:
        out = ["| " + " | ".join(header) + " |",
               "|" + "|".join("---" for _ in header) + "|"]
        out += ["| " + " | ".join(r) + " |" for r in rows]
        return out
    out = [",".join(header)]
    out += [",".join(r) for r in rows]
    return out


def tables(agg: dict, markdown: bool = False, p99: bool = False) -> str:
    """The human/CI-facing report: spans, counters, gauges.

    ``p99`` adds a tail-latency column to the span tables (linear-
    interpolated 99th percentile, utils/statistics.percentile) — central
    tendency alone hides exactly what a multi-tenant latency story is
    about."""
    lines: List[str] = []
    head = (
        f"{agg['n_records']} records · runs={len(agg['runs'])} "
        f"procs={agg['procs']} apps={','.join(agg['apps']) or '-'}"
    )
    lines.append(("### metrics report\n" + head) if markdown else "# " + head)

    if agg["spans"]:
        rows = [
            [name, agg["span_phase"].get(name, "-"), str(st.count()),
             f"{st.min():.6f}", f"{st.trimean():.6f}", f"{st.max():.6f}"]
            + ([f"{st.percentile(99):.6f}"] if p99 else [])
            for name, st in sorted(agg["spans"].items())
        ]
        lines.append("" if markdown else "# spans")
        if markdown:
            lines.append("**spans**")
        lines += _rows_to_table(
            ["span", "phase", "n", "min_s", "trimean_s", "max_s"]
            + (["p99_s"] if p99 else []),
            rows, markdown)

    if agg["counters"]:
        rows = [
            [name, str(c["n"]), _fmt_set(c["value"]), _fmt_set(c["bytes"])]
            for name, c in sorted(agg["counters"].items())
        ]
        lines.append("" if markdown else "# counters")
        if markdown:
            lines.append("**counters**")
        lines += _rows_to_table(["counter", "n", "value", "bytes"],
                                rows, markdown)

    if agg["gauges"]:
        rows = [
            [name, str(st.count()), f"{st.trimean():.6g}"]
            for name, st in sorted(agg["gauges"].items())
        ]
        lines.append("" if markdown else "# gauges")
        if markdown:
            lines.append("**gauges**")
        lines += _rows_to_table(["gauge", "n", "trimean"], rows, markdown)
    return "\n".join(lines)


def _flatten_numeric(obj, prefix: str = "") -> Dict[str, float]:
    """Dotted-path map of every numeric leaf in a baseline JSON — accepts
    BASELINE.json, a bench.py payload ({"metric": ..., "value": ...}), or
    any flat {name: number} map."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        if isinstance(obj.get("metric"), str) and isinstance(
                obj.get("value"), (int, float)):
            out[obj["metric"]] = float(obj["value"])
        for k, v in obj.items():
            out.update(_flatten_numeric(v, f"{prefix}{k}." if prefix or k else ""))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if prefix:
            out[prefix[:-1]] = float(obj)
    return out


def baseline_delta(agg: dict, baseline: dict,
                   markdown: bool = False) -> str:
    """Gauge-vs-baseline ratios for every gauge whose name matches a
    numeric baseline entry (exact name, or last dotted component).

    When two baseline keys share a leaf name, the leaf match is
    AMBIGUOUS: the row is flagged instead of silently ratio-ing against
    whichever key flattened first (an exact full-name match is still
    unambiguous and unaffected)."""
    flat = _flatten_numeric(baseline)
    by_leaf: Dict[str, List[Tuple[str, float]]] = {}
    for k, v in flat.items():
        by_leaf.setdefault(k.split(".")[-1], []).append((k, v))
    rows: List[List[str]] = []
    for name, st in sorted(agg["gauges"].items()):
        match: Optional[Tuple[str, float]] = None
        if name in flat:
            match = (name, flat[name])
        else:
            cands = by_leaf.get(name.split(".")[-1], [])
            if len(cands) > 1:
                rows.append([name, f"{st.trimean():.6g}", "-", "AMBIGUOUS",
                             ";".join(sorted(k for k, _v in cands))])
                continue
            if cands:
                match = cands[0]
        if match is None or match[1] == 0:
            continue
        key, base = match
        rows.append([name, f"{st.trimean():.6g}", f"{base:.6g}",
                     f"{st.trimean() / base:.3f}", key])
    if not rows:
        return ("_no gauge matches a numeric baseline entry_" if markdown
                else "# vs-baseline: no gauge matches a numeric baseline entry")
    lines = ["**vs baseline**"] if markdown else ["# vs baseline"]
    lines += _rows_to_table(
        ["gauge", "trimean", "baseline", "ratio", "baseline_key"],
        rows, markdown)
    return "\n".join(lines)


def _heartbeat_line(hb_path: Optional[str]) -> str:
    """One status line from the watchdog heartbeat file's mtime — the
    same freshness signal the supervisor reads (obs/watchdog.py)."""
    if not hb_path:
        return "heartbeat: (no heartbeat file)"
    try:
        age = time.time() - os.stat(hb_path).st_mtime
    except OSError:
        return f"heartbeat: {hb_path} missing (child not started?)"
    return f"heartbeat: {age:.1f}s ago ({hb_path})"


def follow(paths: List[str], *, interval_s: float = 2.0, count: int = 0,
           markdown: bool = False, p99: bool = False,
           heartbeat: Optional[str] = None, out=None) -> int:
    """Live tail: re-read the (growing) metrics files every
    ``interval_s`` and re-render the span/gauge tables in place.

    Files that do not exist yet are simply waited for (a run-status view
    usually starts before the run). ``count`` bounds the redraws (0 =
    until interrupted — the normal interactive mode)."""
    out = out or sys.stdout
    hb = heartbeat or os.environ.get(HEARTBEAT_FILE_ENV) or None
    it = 0
    # ^C is the documented way OUT of the live view — it must exit
    # cleanly wherever it lands (with big files most wall time is in
    # load/aggregate/render, not the sleep)
    try:
        while True:
            it += 1
            have = [p for p in paths if os.path.exists(p)]
            try:
                records, errors = load(have)
            except OSError as e:
                # a file can vanish between the exists() filter and open()
                # (watchdog retry ladders rotate child logs) — wait for
                # the next redraw instead of dying mid-view
                records, errors = [], [str(e)]
            body = (tables(aggregate(records), markdown=markdown, p99=p99)
                    if records
                    else f"(waiting for records in {', '.join(paths)})")
            if getattr(out, "isatty", lambda: False)():
                out.write("\x1b[2J\x1b[H")  # clear + home: render in place
            stamp = time.strftime("%H:%M:%S")
            out.write(f"-- follow #{it} @ {stamp} · "
                      f"{len(have)}/{len(paths)} file(s) · "
                      f"{len(errors)} schema error(s) · "
                      f"{_heartbeat_line(hb)}\n")
            out.write(body + "\n")
            out.flush()
            if count and it >= count:
                return 0
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return 0


def follow_status(path: str, *, interval_s: float = 2.0, count: int = 0,
                  once: bool = False, out=None) -> int:
    """The top-like run-status view: render the atomic snapshot file
    (obs/status.py) once, or re-render it in place every ``interval_s``
    (``--follow``). A missing/unparseable file is waited for — the view
    usually starts before the run."""
    from ..obs import status as status_mod

    out = out or sys.stdout
    it = 0
    try:
        while True:
            it += 1
            doc = status_mod.read_status(path)
            if doc is None:
                body = f"(waiting for a status snapshot at {path})"
            else:
                errs = status_mod.validate_status(doc)
                body = status_mod.render_status(doc)
                if errs:
                    body += f"\n({len(errs)} schema issue(s): {errs[0]})"
            if once:
                out.write(body + "\n")
                return 0 if doc is not None else 1
            if getattr(out, "isatty", lambda: False)():
                out.write("\x1b[2J\x1b[H")
            out.write(f"-- status #{it} @ {time.strftime('%H:%M:%S')} · "
                      f"{path}\n{body}\n")
            out.flush()
            if count and it >= count:
                return 0
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        description="aggregate telemetry metrics JSONL into trimean tables")
    p.add_argument("paths", nargs="*", help="metrics JSONL file(s)")
    p.add_argument("--markdown", action="store_true",
                   help="markdown tables instead of CSV")
    p.add_argument("--p99", action="store_true",
                   help="add a p99 tail-latency column to the span tables "
                        "(the campaign latency legs' statistic)")
    p.add_argument("--baseline", default="",
                   help="JSON of recorded numbers for a vs-baseline delta")
    p.add_argument("--validate", action="store_true",
                   help="schema-gate mode: exit 1 on any invalid line")
    p.add_argument("--ledger", default="",
                   help="also validate this performance-ledger file "
                        "(obs/ledger.py schema) in --validate mode")
    p.add_argument("--trace-out", default="",
                   help="export the records as a Chrome-trace/Perfetto "
                        "timeline JSON (one lane per (run, proc); fault/"
                        "ckpt markers as instant events)")
    p.add_argument("--follow", action="store_true",
                   help="live tail: re-read growing metrics files and "
                        "re-render in place")
    p.add_argument("--status", default="",
                   help="top-like reader of a run-status snapshot file "
                        "(obs/status.py; written per chunk by the guarded "
                        "loop's --status-file): renders once, or in place "
                        "with --follow")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--follow redraw period in seconds")
    p.add_argument("--follow-count", type=int, default=0,
                   help="stop --follow after N redraws (0 = until ^C)")
    p.add_argument("--heartbeat", default="",
                   help="watchdog heartbeat file whose freshness --follow "
                        "shows (default: $STENCIL_HEARTBEAT_FILE)")
    p.add_argument("--out", default="", help="also write the report here")
    args = p.parse_args(argv)

    # single-purpose modes ignore the other output flags — say so instead
    # of silently producing no artifact
    def _warn_ignored(mode: str, flags: List[Tuple[str, object]]) -> None:
        ignored = [name for name, val in flags if val]
        if ignored:
            print(f"# {mode} mode ignores {', '.join(ignored)}",
                  file=sys.stderr)

    if args.status:
        _warn_ignored("--status", [("--validate", args.validate),
                                   ("--ledger", args.ledger),
                                   ("--trace-out", args.trace_out),
                                   ("--baseline", args.baseline),
                                   ("--out", args.out),
                                   ("metrics paths", args.paths)])
        return follow_status(args.status, interval_s=args.interval,
                             count=args.follow_count,
                             once=not args.follow)
    if not args.paths:
        p.error("at least one metrics JSONL path is required "
                "(or --status FILE)")
    if args.follow:
        _warn_ignored("--follow", [("--validate", args.validate),
                                   ("--ledger", args.ledger),
                                   ("--trace-out", args.trace_out),
                                   ("--baseline", args.baseline),
                                   ("--out", args.out)])
        return follow(args.paths, interval_s=args.interval,
                      count=args.follow_count, markdown=args.markdown,
                      p99=args.p99, heartbeat=args.heartbeat or None)
    if args.validate:
        _warn_ignored("--validate", [("--trace-out", args.trace_out),
                                     ("--baseline", args.baseline),
                                     ("--out", args.out)])

    records, errors = load(args.paths)
    if errors:
        for e in errors:
            print(f"SCHEMA: {e}")
    if args.validate:
        ledger_msg = ""
        if args.ledger:
            from ..obs import ledger as ledger_mod

            try:
                if not os.path.exists(args.ledger):
                    # load_ledger treats a missing file as an empty ledger
                    # (fine for a first append) — but a GATE asked to
                    # validate a path that is not there must fail, not
                    # silently validate nothing
                    raise ledger_mod.LedgerError(
                        f"{args.ledger}: no such ledger file")
                n_led = len(ledger_mod.load_ledger(args.ledger))
                ledger_msg = f", ledger: {n_led} valid entries"
            except ledger_mod.LedgerError as e:
                print(f"SCHEMA: LEDGER: {e}")
                errors.append(f"LEDGER: {e}")
                ledger_msg = ", ledger: INVALID"
        print(f"{len(records)} valid records, {len(errors)} schema errors"
              + ledger_msg)
        return 1 if errors or not records else 0

    # past this point nothing reads the ledger — a CI line that forgot
    # --validate must hear that its ledger check did not happen
    _warn_ignored("report", [("--ledger", args.ledger)])

    if args.trace_out:
        from ..obs import trace_export

        n_ev = trace_export.write_trace(args.trace_out, records)
        print(f"# trace: {n_ev} events -> {args.trace_out}")

    agg = aggregate(records)
    text = tables(agg, markdown=args.markdown, p99=args.p99)
    if args.baseline:
        with open(args.baseline) as f:
            text += "\n" + baseline_delta(agg, json.load(f),
                                          markdown=args.markdown)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
