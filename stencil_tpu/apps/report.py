"""report — aggregate telemetry metrics JSONL into trimean tables.

Consumes the one-JSON-object-per-line files the bench apps write via
``--metrics-out`` (schema: stencil_tpu/obs/telemetry.py), across any
number of files/processes/runs, and reports:

- spans: per-name count / min / trimean / max seconds
  (``utils/statistics.Statistics`` — the reference's canonical trimean,
  bin/statistics.hpp:17);
- counters: the static byte/count truth (collective census, DMA bytes,
  logical/moved exchange bytes) with cross-record consistency flagged;
- gauges: per-name trimean (throughputs, timer buckets);
- an optional vs-baseline delta against a JSON file of recorded numbers
  (BASELINE.json / a bench.py payload / any flat {name: number} map).

``--validate`` makes it the CI schema gate: every line must parse and
satisfy the telemetry schema, or the exit code is 1.

Usage:
  python -m stencil_tpu.apps.report m1.jsonl [m2.jsonl ...] [--markdown]
  python -m stencil_tpu.apps.report metrics.jsonl --validate
  python -m stencil_tpu.apps.report metrics.jsonl --baseline BASELINE.json
"""

from __future__ import annotations

import argparse
import json
from typing import Dict, List, Optional, Tuple

from ..obs import telemetry
from ..utils.statistics import Statistics


def load(paths: List[str]) -> Tuple[List[dict], List[str]]:
    """Read + schema-validate records from JSONL files.

    Returns (valid records, error strings); invalid lines are reported,
    not silently dropped into the aggregate.
    """
    records: List[dict] = []
    errors: List[str] = []
    for path in paths:
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    errors.append(f"{path}:{i}: unparseable JSON ({e})")
                    continue
                errs = telemetry.validate_record(rec)
                if errs:
                    errors.extend(f"{path}:{i}: {e}" for e in errs)
                else:
                    records.append(rec)
    return records, errors


def _agg_key(rec: dict) -> str:
    """Aggregation key: the record name, split per exchange method when a
    ``method`` tag is present — a method-ablation run intentionally emits
    different census/byte/timing values per method, and folding them under
    one name would mix timings and false-positive the DISAGREE flag. The
    ``batched`` tag splits the same way: a quantity-batching A/B run emits
    both legs' truths (e.g. ``exchange.permutes_per_quantity`` 6/Q vs 6),
    and averaging them would read as neither."""
    name = rec["name"]
    tags = [str(rec[t]) for t in ("method", "batched") if t in rec]
    if tags:
        return f"{name}[{','.join(tags)}]"
    return name


def aggregate(records: List[dict]) -> dict:
    """Fold records into per-name statistics (per-method names when
    tagged, see :func:`_agg_key`).

    Spans and gauges aggregate across processes AND runs (each sample
    keeps equal weight — the reference trimean discipline). Counters are
    static truths PER CONFIGURATION — one key can legitimately carry
    several distinct values (a radius sweep in one run, multiple runs
    appended to one file), so the table shows the distinct set as a range
    rather than presuming agreement.
    """
    spans: Dict[str, Statistics] = {}
    span_phase: Dict[str, str] = {}
    gauges: Dict[str, Statistics] = {}
    counters: Dict[str, dict] = {}
    runs, procs, apps = set(), set(), set()
    for rec in records:
        runs.add(rec["run"])
        procs.add(rec["proc"])
        if "app" in rec:
            apps.add(rec["app"])
        kind, name = rec["kind"], _agg_key(rec)
        if kind == "span":
            spans.setdefault(name, Statistics()).insert(rec["seconds"])
            if "phase" in rec:
                span_phase[name] = rec["phase"]
        elif kind == "gauge":
            gauges.setdefault(name, Statistics()).insert(rec["value"])
        elif kind == "counter":
            c = counters.setdefault(
                name, {"n": 0, "value": set(), "bytes": set()}
            )
            c["n"] += 1
            if "value" in rec:
                c["value"].add(rec["value"])
            if "bytes" in rec:
                c["bytes"].add(rec["bytes"])
    return {
        "spans": spans,
        "span_phase": span_phase,
        "gauges": gauges,
        "counters": counters,
        "runs": sorted(runs),
        "procs": sorted(procs),
        "apps": sorted(apps),
        "n_records": len(records),
    }


def _fmt_set(s: set) -> str:
    if not s:
        return "-"
    if len(s) == 1:
        return str(next(iter(s)))
    return f"{min(s)}..{max(s)} ({len(s)} distinct)"


def _rows_to_table(header: List[str], rows: List[List[str]],
                   markdown: bool) -> List[str]:
    if markdown:
        out = ["| " + " | ".join(header) + " |",
               "|" + "|".join("---" for _ in header) + "|"]
        out += ["| " + " | ".join(r) + " |" for r in rows]
        return out
    out = [",".join(header)]
    out += [",".join(r) for r in rows]
    return out


def tables(agg: dict, markdown: bool = False) -> str:
    """The human/CI-facing report: spans, counters, gauges."""
    lines: List[str] = []
    head = (
        f"{agg['n_records']} records · runs={len(agg['runs'])} "
        f"procs={agg['procs']} apps={','.join(agg['apps']) or '-'}"
    )
    lines.append(("### metrics report\n" + head) if markdown else "# " + head)

    if agg["spans"]:
        rows = [
            [name, agg["span_phase"].get(name, "-"), str(st.count()),
             f"{st.min():.6f}", f"{st.trimean():.6f}", f"{st.max():.6f}"]
            for name, st in sorted(agg["spans"].items())
        ]
        lines.append("" if markdown else "# spans")
        if markdown:
            lines.append("**spans**")
        lines += _rows_to_table(
            ["span", "phase", "n", "min_s", "trimean_s", "max_s"],
            rows, markdown)

    if agg["counters"]:
        rows = [
            [name, str(c["n"]), _fmt_set(c["value"]), _fmt_set(c["bytes"])]
            for name, c in sorted(agg["counters"].items())
        ]
        lines.append("" if markdown else "# counters")
        if markdown:
            lines.append("**counters**")
        lines += _rows_to_table(["counter", "n", "value", "bytes"],
                                rows, markdown)

    if agg["gauges"]:
        rows = [
            [name, str(st.count()), f"{st.trimean():.6g}"]
            for name, st in sorted(agg["gauges"].items())
        ]
        lines.append("" if markdown else "# gauges")
        if markdown:
            lines.append("**gauges**")
        lines += _rows_to_table(["gauge", "n", "trimean"], rows, markdown)
    return "\n".join(lines)


def _flatten_numeric(obj, prefix: str = "") -> Dict[str, float]:
    """Dotted-path map of every numeric leaf in a baseline JSON — accepts
    BASELINE.json, a bench.py payload ({"metric": ..., "value": ...}), or
    any flat {name: number} map."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        if isinstance(obj.get("metric"), str) and isinstance(
                obj.get("value"), (int, float)):
            out[obj["metric"]] = float(obj["value"])
        for k, v in obj.items():
            out.update(_flatten_numeric(v, f"{prefix}{k}." if prefix or k else ""))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if prefix:
            out[prefix[:-1]] = float(obj)
    return out


def baseline_delta(agg: dict, baseline: dict,
                   markdown: bool = False) -> str:
    """Gauge-vs-baseline ratios for every gauge whose name matches a
    numeric baseline entry (exact name, or last dotted component)."""
    flat = _flatten_numeric(baseline)
    by_leaf: Dict[str, Tuple[str, float]] = {}
    for k, v in flat.items():
        by_leaf.setdefault(k.split(".")[-1], (k, v))
    rows: List[List[str]] = []
    for name, st in sorted(agg["gauges"].items()):
        match: Optional[Tuple[str, float]] = None
        if name in flat:
            match = (name, flat[name])
        elif name.split(".")[-1] in by_leaf:
            match = by_leaf[name.split(".")[-1]]
        if match is None or match[1] == 0:
            continue
        key, base = match
        rows.append([name, f"{st.trimean():.6g}", f"{base:.6g}",
                     f"{st.trimean() / base:.3f}", key])
    if not rows:
        return ("_no gauge matches a numeric baseline entry_" if markdown
                else "# vs-baseline: no gauge matches a numeric baseline entry")
    lines = ["**vs baseline**"] if markdown else ["# vs baseline"]
    lines += _rows_to_table(
        ["gauge", "trimean", "baseline", "ratio", "baseline_key"],
        rows, markdown)
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        description="aggregate telemetry metrics JSONL into trimean tables")
    p.add_argument("paths", nargs="+", help="metrics JSONL file(s)")
    p.add_argument("--markdown", action="store_true",
                   help="markdown tables instead of CSV")
    p.add_argument("--baseline", default="",
                   help="JSON of recorded numbers for a vs-baseline delta")
    p.add_argument("--validate", action="store_true",
                   help="schema-gate mode: exit 1 on any invalid line")
    p.add_argument("--out", default="", help="also write the report here")
    args = p.parse_args(argv)

    records, errors = load(args.paths)
    if errors:
        for e in errors:
            print(f"SCHEMA: {e}")
    if args.validate:
        print(f"{len(records)} valid records, {len(errors)} schema errors")
        return 1 if errors or not records else 0

    agg = aggregate(records)
    text = tables(agg, markdown=args.markdown)
    if args.baseline:
        with open(args.baseline) as f:
            text += "\n" + baseline_delta(agg, json.load(f),
                                          markdown=args.markdown)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
