"""pingpong — inter-device transfer latency/bandwidth microbenchmark.

TPU-native analogue of the reference's MPI ping-pong (reference:
bin/pingpong.cu): instead of MPI_Send/Recv between ranks, a buffer is
``ppermute``d from device 0 to device 1 and back inside one compiled loop
over a 2-device mesh. Reports per-hop latency and bandwidth per message
size — the raw cost of the collective the whole transport layer rides on.

Usage: python -m stencil_tpu.apps.pingpong --min-bytes 8 --max-bytes 16777216
"""

from __future__ import annotations

import argparse
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.sync import hard_sync
from ..utils import logging as log


def run(min_bytes=8, max_bytes=1 << 24, iters=100, devices=None):
    devices = list(devices) if devices is not None else jax.devices()
    nd = min(2, len(devices))
    if nd < 2:
        log.warn("pingpong needs 2 devices; measuring self-permute on 1")
    perm = [(0, 1), (1, 0)] if nd == 2 else [(0, 0)]
    mesh = Mesh(np.asarray(devices[:nd]), ("p",))
    pspec = P("p")

    rows = []
    nbytes = min_bytes
    while nbytes <= max_bytes:
        n = max(1, nbytes // 4)

        def body(x):
            def it(_, x):
                x = lax.ppermute(x, "p", perm)  # ping
                return lax.ppermute(x, "p", perm)  # pong

            return lax.fori_loop(0, iters, it, x)

        fn = jax.jit(
            jax.shard_map(body, mesh=mesh, in_specs=pspec, out_specs=pspec),
            donate_argnums=0,
        )
        x = jax.device_put(
            jnp.zeros((nd, n), jnp.float32), NamedSharding(mesh, pspec)
        )
        x = fn(x)  # compile + warm
        hard_sync(x)
        t0 = time.perf_counter()
        x = fn(x)
        hard_sync(x)
        dt = time.perf_counter() - t0
        hops = 2 * iters
        rows.append(
            {
                "bytes": n * 4,
                "latency_us": dt / hops * 1e6,
                "gb_per_s": n * 4 * hops / dt / 1e9,
            }
        )
        nbytes *= 4
    return rows


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="ppermute ping-pong microbenchmark")
    p.add_argument("--min-bytes", type=int, default=8)
    p.add_argument("--max-bytes", type=int, default=1 << 24)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--cpu", type=int, default=0)
    from ._bench_common import add_metrics_flags, finish_metrics, start_metrics
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    rec = start_metrics(args, "pingpong")
    print("bytes,latency (us),GB/s")
    for row in run(args.min_bytes, args.max_bytes, args.iters):
        print(f"{row['bytes']},{row['latency_us']:.2f},{row['gb_per_s']:.3f}")
        rec.gauge("pingpong.latency_us", row["latency_us"], phase="exchange",
                  unit="us", bytes=row["bytes"])
        rec.gauge("pingpong.gb_per_s", row["gb_per_s"], phase="exchange",
                  bytes=row["bytes"])
    finish_metrics(rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
