"""bench-pack — halo pack/unpack primitive throughput per direction.

TPU-native port of the reference pack-kernel benchmark (reference:
bin/bench_pack.cu): for each of the 26 directions, time gathering the halo
region into a flat buffer and scattering it back. On TPU the pack kernel is
``lax.dynamic_slice`` + reshape and unpack is ``dynamic_update_slice`` —
this measures those primitives fused in a loop on one device.

Usage: python -m stencil_tpu.apps.bench_pack --x 512 --y 512 --z 512 --iters 50
"""

from __future__ import annotations

import argparse
import time
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..geometry import DIRECTIONS_26, Dim3, Radius, halo_rect, raw_size
from ..utils.sync import hard_sync


def pack_fn(rect, iters):
    zyx = (
        slice(rect.lo.z, rect.hi.z),
        slice(rect.lo.y, rect.hi.y),
        slice(rect.lo.x, rect.hi.x),
    )

    @jax.jit
    def fn(arr, acc):
        def body(_, carry):
            arr, acc = carry
            buf = arr[zyx].reshape(-1)  # pack: gather to flat buffer
            arr = arr.at[zyx].set(buf.reshape(arr[zyx].shape) + 1)  # unpack
            return arr, acc + buf[0]

        return lax.fori_loop(0, iters, body, (arr, acc))

    return fn


def run(x, y, z, radius=3, iters=50, device=None):
    device = device or jax.devices()[0]
    r = Radius.constant(radius)
    size = Dim3(x, y, z)
    padded = raw_size(size, r)
    arr = jax.device_put(
        jnp.zeros((padded.z, padded.y, padded.x), jnp.float32), device
    )
    rows = []
    for d in DIRECTIONS_26:
        rect = halo_rect(d, size, r, halo=True)
        bytes_ = rect.extent().flatten() * 4
        fn = pack_fn(rect, iters)
        arr, acc = fn(arr, jnp.float32(0))  # compile + warm
        hard_sync(arr)
        t0 = time.perf_counter()
        arr, acc = fn(arr, acc)
        hard_sync(arr)
        dt = (time.perf_counter() - t0) / iters
        rows.append(
            {
                "dir": (d.x, d.y, d.z),
                "bytes": bytes_,
                "s_per_op": dt,
                "gb_per_s": 2 * bytes_ / dt / 1e9,  # pack + unpack traffic
            }
        )
    return rows


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="halo pack/unpack primitive benchmark")
    p.add_argument("--x", type=int, default=512)
    p.add_argument("--y", type=int, default=512)
    p.add_argument("--z", type=int, default=512)
    p.add_argument("--radius", type=int, default=3)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--cpu", type=int, default=0)
    from ._bench_common import add_metrics_flags, finish_metrics, start_metrics
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    rec = start_metrics(args, "bench_pack")
    print("dir,bytes,s/op,GB/s")
    for row in run(args.x, args.y, args.z, radius=args.radius, iters=args.iters):
        d = row["dir"]
        print(f"({d[0]} {d[1]} {d[2]}),{row['bytes']},{row['s_per_op']:e},{row['gb_per_s']:.2f}")
        rec.gauge("bench_pack.gb_per_s", row["gb_per_s"], phase="compute",
                  dir=f"{d[0]},{d[1]},{d[2]}", bytes=row["bytes"])
    finish_metrics(rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
