"""plan-tool — inspect, seed, prune, and run the exchange-plan DB.

The operator's window into the plan/ subsystem (the analogue of
``ckpt_tool`` for checkpoints):

- ``show``      list every tuned entry (config -> choice, provenance);
- ``explain``   one config's DB entry + static cost ranking + the chosen
                plan's ExchangePlan IR (phases, permute pairs, bytes);
- ``prune``     drop entries by platform / source / age;
- ``seed``      insert the RECORDED CPU-mesh verdicts (BASELINE.md
                rounds 7/10) so fresh deployments replay them without
                re-benching;
- ``autotune``  tune one config now (the CI plan gate's entry point) —
                a DB hit performs zero probes and says so;
- ``calibrate`` fit calibration constants from a run's attribution
                records (``plan.attrib.phase``) and install the
                ``fitted(n=…, r2=…)`` row in the DB — the
                predict→measure→refit loop's refit step;
- ``calibration`` show/diff the installed fitted rows vs the modeled
                defaults.

``show``/``explain``/``prune``/``seed``/``calibrate``/``calibration``
are jax-free: they run without a backend (the cost model is pure
geometry and the fit is pure stdlib). Only ``autotune`` compiles.

Usage: python -m stencil_tpu.apps.plan_tool show --db plans.json
       python -m stencil_tpu.apps.plan_tool explain --db plans.json \
           --x 128 --y 128 --z 128 --radius 2 --quantities 4 --ndev 8
       python -m stencil_tpu.apps.plan_tool seed --db plans.json
       python -m stencil_tpu.apps.plan_tool autotune --db plans.json \
           --cpu 8 --x 24 --y 24 --z 24 --quantities 4
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

from ..plan import db as plandb
from ..plan.ir import PlanChoice, PlanConfig


def _add_config_flags(p) -> None:
    p.add_argument("--x", type=int, default=24)
    p.add_argument("--y", type=int, default=24)
    p.add_argument("--z", type=int, default=24)
    p.add_argument("--radius", type=int, default=2,
                   help="uniform radius of the config key")
    p.add_argument("--quantities", type=int, default=1)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--ndev", type=int, default=8)
    p.add_argument("--platform", default="cpu")


def _config_from(args) -> PlanConfig:
    from ..geometry import Dim3, Radius

    return PlanConfig.make(
        Dim3(args.x, args.y, args.z), Radius.constant(args.radius),
        [args.dtype] * args.quantities, args.ndev, args.platform,
    )


def _entry_row(key: str, entry: dict) -> str:
    cfg = json.loads(key)
    choice = PlanChoice.from_json(entry["choice"])
    g = cfg["grid"]
    qs = ",".join(f"{n}x{dt}" for dt, n in cfg["quantities"])
    measured = entry.get("measured_s")
    return (
        f"{g[0]}x{g[1]}x{g[2]},{qs},{cfg['ndev']},{cfg['platform']},"
        f"{choice.label()},{entry.get('source')},"
        f"{'' if measured is None else f'{measured:.6f}'}"
    )


def cmd_show(args) -> int:
    db = plandb.load_db(args.db)
    print("grid,quantities,ndev,platform,choice,source,measured_s")
    for key in sorted(db["entries"]):
        print(_entry_row(key, db["entries"][key]))
    print(f"# {len(db['entries'])} entries")
    return 0


def cmd_explain(args) -> int:
    from ..plan.cost import enumerate_candidates, feasible, rank
    from ..plan.ir import build_plan

    config = _config_from(args)
    print(f"config key: {config.key()}")
    entry = None
    calibration = None
    cal_note = "modeled(default)"
    if args.db:
        db = plandb.load_db(args.db)
        entry = plandb.lookup(db, config)
        # price the ranking with the DB's installed calibration, exactly
        # as an autotune run against this DB would (plan/autotune.py)
        cal_row = plandb.lookup_calibration(db, args.platform)
        if cal_row is not None:
            calibration = cal_row["calibration"]
            cal_note = str(cal_row.get("provenance", "fitted"))
    if entry is not None:
        print(f"DB entry: {PlanChoice.from_json(entry['choice']).label()} "
              f"(source {entry['source']}, measured_s "
              f"{entry.get('measured_s')})")
    else:
        print("DB entry: none (an --autotune run would probe)")
    ranked = rank(config, enumerate_candidates(config), calibration)
    print(f"static ranking ({len(ranked)} feasible candidates; "
          f"calibration: {cal_note}):")
    for cost, choice in ranked[: args.top]:
        extra = (f" dmas={cost.dmas}" if choice.method == "remote-dma"
                 else "")
        print(f"  {choice.label():45s} {cost.total_s * 1e3:9.3f} ms/step  "
              f"permutes={cost.collectives} wire={cost.wire_bytes}{extra}")
    if args.method:
        # explain one method's plan IR explicitly (e.g. remote-dma with
        # its 0-ppermute census prediction, DMA count, and the
        # wire_dtype-compressed byte model) instead of the ranked best
        best = next((ch for _c, ch in ranked if ch.method == args.method),
                    None)
        if best is None:
            print(f"no feasible {args.method} candidate for this config")
            return 1
    else:
        best = (PlanChoice.from_json(entry["choice"]) if entry is not None
                else ranked[0][1] if ranked else None)
    if best is not None:
        feas = feasible(config, best)
        if feas is not None:
            spec, mesh_dim, resident = feas
            plan = build_plan(spec, mesh_dim, best.method,
                              best.batch_quantities, resident,
                              wire_dtype=args.wire_dtype or None)
            print("plan IR of the "
                  + (f"requested {args.method}" if args.method
                     else "DB" if entry is not None else "best static")
                  + " choice:")
            print(plan.describe())
            if args.placement:
                _explain_placement(args, config, best, spec, mesh_dim)
            if args.hierarchy:
                _explain_hierarchy(args, config, best, spec, mesh_dim,
                                   resident)
    return 0


def _explain_placement(args, config, choice, spec, mesh_dim) -> None:
    """The ``explain --placement`` table: the choice's block→device
    assignment plus the per-pair wire-bytes x link-cost products the
    QAP minimized. Jax-free: link costs come from ``--link-costs``
    (a JSON ndev x ndev matrix, e.g. a dumped
    ``parallel.topology.link_cost_matrix``) or default to uniform —
    under which every placement prices identically, and the table says
    so instead of implying a win."""
    import numpy as np

    from ..geometry import Dim3
    from ..plan.cost import placement_cost, placement_wire_matrix

    md = Dim3.of(mesh_dim)
    n = md.flatten()
    w = placement_wire_matrix(spec, md,
                              per_cell_bytes=sum(config.itemsizes()))
    if args.link_costs:
        with open(args.link_costs) as fh:
            link = np.asarray(json.load(fh), dtype=np.float64)
        if link.shape != (n, n):
            raise SystemExit(
                f"--link-costs matrix is {link.shape}; the mesh has "
                f"{n} positions")
        src = args.link_costs
    else:
        link = np.ones((n, n))
        np.fill_diagonal(link, 0.0)
        src = "uniform default (pass --link-costs for a real fabric)"
    f = (list(choice.placement) if choice.placement is not None
         else list(range(n)))
    print(f"placement ({'tuned' if choice.placement is not None else 'identity'}; link costs: {src}):")
    for i in range(n):
        iz, rem = divmod(i, md.x * md.y)
        iy, ix = divmod(rem, md.x)
        print(f"  mesh ({ix},{iy},{iz}) -> device {f[i]}")
    print("per-pair wire-bytes x link-cost (placed devices):")
    print("  pair(mesh),devices,wire_bytes,link_cost,product")
    for a in range(n):
        for b in range(n):
            if b <= a or (w[a, b] == 0 and w[b, a] == 0):
                continue
            wb = w[a, b] + w[b, a]
            lc = link[f[a], f[b]]
            print(f"  {a}-{b},{f[a]}-{f[b]},{int(wb)},{lc:g},"
                  f"{wb * lc:g}")
    ident = placement_cost(w, link)
    placed = placement_cost(w, link, f)
    print(f"total modeled wire cost: placed {placed:g} vs identity "
          f"{ident:g}"
          + (f" ({ident / placed:.3f}x better)" if placed < ident else
             " (identity-equivalent)" if placed == ident else
             " (WORSE than identity — re-tune)"))


def _explain_hierarchy(args, config, choice, spec, mesh_dim,
                       resident) -> None:
    """The ``explain --hierarchy`` view: the two-level (ICI+DCN)
    decomposition of the choice — one DCN phase per feasible outer
    split (segment geometry, cross-host transfers, DCN wire bytes over
    the inner plan) plus the two-level placement the QAP composes.
    Jax-free: the fabric comes from ``--link-costs`` (file) and
    ``--host-map`` (inline JSON device->host list); both default to the
    uniform single-tier fabric, under which the solver returns identity
    and the table says "flat-equivalent" instead of implying a win."""
    import numpy as np

    from ..geometry import Dim3
    from ..plan.cost import (placement_cost, placement_wire_matrix,
                             solve_two_level_placement)
    from ..plan.ir import build_plan, validate_hierarchy

    md = Dim3.of(mesh_dim)
    n = md.flatten()
    if choice.hierarchy is not None:
        splits = [tuple(choice.hierarchy)]
        print(f"hierarchy (tuned into the choice): "
              f"{splits[0][1]} hosts on {splits[0][0]}")
    else:
        splits = [(ax, args.hosts) for ax in ("x", "y", "z")
                  if validate_hierarchy((ax, args.hosts), md) is None]
        if not splits:
            print(f"hierarchy: no axis of mesh {tuple(md)} divides "
                  f"into {args.hosts} host(s) — flat only")
            return
    if args.link_costs:
        with open(args.link_costs) as fh:
            link = np.asarray(json.load(fh), dtype=np.float64)
        if link.shape != (n, n):
            raise SystemExit(
                f"--link-costs matrix is {link.shape}; the mesh has "
                f"{n} positions")
        fab = args.link_costs
    else:
        link = np.ones((n, n))
        np.fill_diagonal(link, 0.0)
        fab = "uniform default (pass --link-costs for a real fabric)"
    host_map = None
    if args.host_map:
        host_map = [int(h) for h in json.loads(args.host_map)]
        if len(host_map) != n:
            raise SystemExit(
                f"--host-map lists {len(host_map)} devices; the mesh "
                f"has {n} positions")
    itemsizes = config.itemsizes()
    w = placement_wire_matrix(spec, md, per_cell_bytes=sum(itemsizes))
    print(f"link costs: {fab}; host map: "
          f"{host_map if host_map is not None else 'contiguous split'}")
    for axis, h in splits:
        plan = build_plan(spec, md, choice.method,
                          choice.batch_quantities, resident,
                          wire_dtype=args.wire_dtype or None,
                          fused=choice.is_fused,
                          persistent=choice.is_persistent,
                          hierarchy=(axis, h))
        dp = plan.dcn_phases[0]
        nq = config.num_quantities
        ngroups = len({dt for dt, _n in config.quantities})
        print(f"outer split {axis} x {h} hosts (seg={dp.seg}, "
              f"slice_devices={dp.slice_devices}):")
        print(f"  DCN level: {plan.dcn_transfers_per_exchange(nq, ngroups)}"
              f" cross-host copies/exchange, "
              f"{plan.dcn_wire_bytes(itemsizes)} bytes (host-orchestrated"
              f" — the census sees 0 ppermutes)")
        print(f"  ICI level: {plan.collectives_per_exchange(nq, ngroups)}"
              f" permutes/exchange, {plan.wire_bytes(itemsizes)} bytes "
              f"(the flat plan's inner pins, unchanged)")
        hp, comp = solve_two_level_placement(w, link, md, (axis, h),
                                             host_map)
        if hp is None and comp is None:
            print("  two-level placement: identity — this fabric is "
                  "flat-equivalent (the split changes the transport, "
                  "not the halos or bytes; nothing to place)")
            continue
        print(f"  host placement (host slot -> host group): "
              f"{list(hp) if hp is not None else 'identity'}")
        print(f"  composed device placement: "
              f"{list(comp) if comp is not None else 'identity'}")
        ident = placement_cost(w, link)
        placed = (placement_cost(w, link, comp) if comp is not None
                  else ident)
        print(f"  modeled wire cost: placed {placed:g} vs identity "
              f"{ident:g}"
              + (f" ({ident / placed:.3f}x better)" if placed < ident
                 else " (identity-equivalent)" if placed == ident
                 else " (WORSE than identity — re-tune)"))


def cmd_prune(args) -> int:
    db = plandb.load_db(args.db)
    n = plandb.prune_db(
        db, platform=args.platform or None, source=args.source or None,
        older_than_s=args.older_than_days * 86400.0
        if args.older_than_days is not None else None,
    )
    plandb.save_db(args.db, db)
    print(f"pruned {n} entries ({len(db['entries'])} remain)")
    return 0


# The recorded CPU-mesh verdicts (BASELINE.md rounds 7/10): 128^3,
# uniform radius 2, fp32, 2x2x2 partition on the 8-device CPU mesh.
# axis-composed + batching won every measured comparison there:
# manual-over-auto ~4% (47.6 vs 49.5 ms), direct26 4.2x slower on 1.9x
# fewer bytes, batched-over-per-quantity 1.43x at Q=4 / 1.65x at Q=8.
_SEED_ROWS = (
    (1, 8.85e-3, "round 10: Q=1 batched == per-quantity (same program)"),
    (4, 26.2e-3, "round 7/10: per-quantity 37.4 ms (1.43x); direct26 "
                 "4.2x slower on 1.9x fewer bytes; manual over auto ~4%"),
    (8, 42.9e-3, "round 10: per-quantity 70.6 ms (1.65x); astaroth "
                 "8-field exchange 1.46x by the same mechanism"),
)


def cmd_seed(args) -> int:
    from ..geometry import Dim3, Radius

    db = plandb.load_db(args.db)
    n = 0
    for q, measured_s, note in _SEED_ROWS:
        config = PlanConfig.make(Dim3(128, 128, 128), Radius.constant(2),
                                 ["float32"] * q, 8, args.platform)
        if plandb.lookup(db, config) is not None and not args.force:
            continue
        choice = PlanChoice(partition=(2, 2, 2), method="axis-composed",
                            batch_quantities=True)
        plandb.record(db, plandb.make_entry(
            config, choice, "seed", measured_s=measured_s,
            note=f"BASELINE.md recorded verdict — {note}",
        ))
        n += 1
    plandb.save_db(args.db, db)
    print(f"seeded {n} entries into {args.db} "
          f"({len(db['entries'])} total)")
    return 0


def cmd_calibrate(args) -> int:
    """Fit a calibration row from attribution evidence and install it
    in the plan DB (the predict→measure→refit loop's refit step).
    Jax-free: the evidence is a metrics JSONL or the LEDGER, the fit is
    pure stdlib, and the DB write is the same atomic-rename path every
    other subcommand uses."""
    from ..obs import telemetry
    from ..plan import calibrate as cal
    from ..plan.cost import DEFAULT_CALIBRATION

    if bool(args.from_metrics) == bool(args.from_ledger):
        raise SystemExit(
            "calibrate needs exactly one evidence source: "
            "--from-metrics METRICS.jsonl or --from-ledger LEDGER.jsonl")
    if args.from_metrics:
        with open(args.from_metrics) as f:
            lines = f.readlines()
        n_ok, errs = telemetry.validate_jsonl(lines)
        if errs:
            raise SystemExit(
                f"{args.from_metrics}: {len(errs)} schema-invalid records "
                f"(first: {errs[0]}) — refusing to fit from a corrupt "
                "metrics file")
        records = [json.loads(ln) for ln in lines if ln.strip()]
        samples = cal.samples_from_records(records)
        src = args.from_metrics
    else:
        from ..obs.ledger import load_ledger

        samples = cal.samples_from_ledger(load_ledger(args.from_ledger))
        src = args.from_ledger
    if getattr(args, "phase", None):
        # one phase = one measurement population: probe chunks and the
        # epilogue loop amortize dispatch overhead differently, and a
        # fit across both prices neither correctly
        want = set(args.phase)
        samples = [s for s in samples if s.phase in want]
        if not samples:
            raise SystemExit(
                f"no attribution samples match --phase "
                f"{sorted(want)} in {src}")
    try:
        row = cal.fit(samples, platform=args.platform)
    except cal.CalibrationError as e:
        raise SystemExit(f"calibration fit refused: {e}")
    db = plandb.load_db(args.db)
    plandb.record_calibration(db, args.platform, row)
    plandb.save_db(args.db, db)
    print(f"fitted {args.platform} calibration from {len(samples)} "
          f"samples ({src}) -> {args.db}")
    print(f"provenance: {row['provenance']}"
          + ("" if row["bandwidth_fit"]
             else "  [bandwidth pinned at the modeled default: the "
                  "samples share one (collectives, bytes) point]"))
    for name, fitted, base_v in cal.diff_rows(row, DEFAULT_CALIBRATION):
        print(f"  {name:45s} {fitted:.6e}  (modeled {base_v:.6e}, "
              f"{fitted / base_v:.2f}x)")
    if getattr(args, "metrics_out", ""):
        rec = telemetry.configure(metrics_out=args.metrics_out,
                                  app="plan_tool",
                                  run_id=getattr(args, "run_id", "") or None,
                                  config=vars(args))
        rec.meta("calibration.fitted", platform=args.platform,
                 n=int(row["n"]), provenance=row["provenance"],
                 r2=float(row["r2"]))
        rec.close()
    return 0


def cmd_calibration(args) -> int:
    """``calibration show``: the DB's fitted rows. ``calibration diff``:
    fitted constants vs the modeled defaults, one line per constant."""
    from ..plan import calibrate as cal
    from ..plan.cost import DEFAULT_CALIBRATION

    db = plandb.load_db(args.db)
    cals = db.get("calibrations") or {}
    if args.action == "show":
        if not cals:
            print("no fitted calibrations (modeled defaults apply)")
            return 0
        print("platform,provenance,n,r2,bandwidth_fit")
        for platform in sorted(cals):
            row = cals[platform]
            print(f"{platform},{row['provenance']},{row['n']},"
                  f"{row['r2']:.4f},{row.get('bandwidth_fit', False)}")
        return 0
    # diff
    platforms = [args.platform] if args.platform else sorted(cals)
    if not platforms:
        print("no fitted calibrations to diff (modeled defaults apply)")
        return 0
    for platform in platforms:
        row = cals.get(platform)
        if row is None:
            print(f"{platform}: no fitted row (modeled defaults apply)")
            continue
        print(f"{platform} ({row['provenance']}):")
        print("  constant,fitted,modeled,ratio")
        for name, fitted, base_v in cal.diff_rows(row, DEFAULT_CALIBRATION):
            print(f"  {name},{fitted:.6e},{base_v:.6e},"
                  f"{fitted / base_v:.3f}")
    return 0


def cmd_autotune(args) -> int:
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    from ._bench_common import start_metrics

    start_metrics(args, "plan_tool")
    from ..geometry import Dim3, Radius
    from ..plan.autotune import autotune
    from ..plan.ir import METHODS

    methods = tuple(t for t in args.methods.split(",") if t) or METHODS
    for m in methods:
        if m not in METHODS:
            raise SystemExit(f"unknown method {m!r} (choose from {METHODS})")
    # kernel variants to search (e.g. --variants fused pins the search
    # to the fused compute+exchange candidates, --variants none to the
    # unvariant programs only); default: the unvariant program plus,
    # for remote-dma, the fused variant (cost.enumerate_candidates adds
    # it). Validated like --methods — a typo'd variant must fail here,
    # not land in the DB as a string no lowering recognizes.
    from ..plan.cost import DEFAULT_VARIANTS
    from ..plan.ir import FUSED_VARIANT, PERSISTENT_VARIANT

    if args.variants:
        variants = []
        for t in (s.strip() for s in args.variants.split(",") if s.strip()):
            if t == "none":
                variants.append(None)
            elif t in (FUSED_VARIANT, PERSISTENT_VARIANT):
                variants.append(t)
            else:
                raise SystemExit(
                    f"unknown kernel variant {t!r} (choose from "
                    f"'{FUSED_VARIANT}', '{PERSISTENT_VARIANT}', 'none')")
        variants = tuple(variants)
    else:
        variants = DEFAULT_VARIANTS
    ks = tuple(int(t) for t in args.ks.split(",") if t.strip()) or (1,)
    for k in ks:
        if k < 1:
            raise SystemExit(f"--ks depths must be >= 1, got {k}")
    res = autotune(
        Dim3(args.x, args.y, args.z), Radius.constant(args.radius),
        [args.dtype] * args.quantities,
        devices=jax.devices()[: args.ndev] if args.ndev else None,
        db_path=args.db or None, top_n=args.top_n,
        probe_iters=args.probe_iters, probe=not args.no_probe,
        force=args.force, methods=methods, ks=ks, variants=variants,
    )
    print(f"chosen: {res.choice.label()}")
    print(f"source: {res.source}  cache_hit: {res.cache_hit}  "
          f"probes_run: {res.probes_run}  candidates: {res.candidates}")
    for p in res.probes:
        if "trimean_s" in p:
            print(f"  probe {p['label']:45s} {p['trimean_s'] * 1e3:9.3f} ms")
        else:
            print(f"  probe {p['label']:45s} FAILED: {p.get('error')}")
    from ._bench_common import finish_metrics
    from ..obs import telemetry

    finish_metrics(telemetry.get())
    return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="exchange-plan DB tool")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("show", help="list tuned entries")
    sp.add_argument("--db", required=True)

    sp = sub.add_parser("explain",
                        help="DB entry + static ranking + plan IR of one config")
    sp.add_argument("--db", default="")
    sp.add_argument("--top", type=int, default=8)
    sp.add_argument("--method", default="",
                    choices=("",) + plandb.METHODS,
                    help="dump THIS method's plan IR (e.g. remote-dma: "
                         "0-ppermute prediction + DMA count) instead of "
                         "the ranked best")
    sp.add_argument("--wire-dtype", default="",
                    help="render the plan's wire bytes under this "
                         "wire-compression dtype (e.g. bfloat16)")
    sp.add_argument("--placement", action="store_true",
                    help="also render the choice's block→device "
                         "assignment and the per-pair wire-bytes x "
                         "link-cost table the placement QAP minimized")
    sp.add_argument("--link-costs", default="",
                    help="JSON ndev x ndev link-cost matrix for "
                         "--placement/--hierarchy (e.g. a dumped "
                         "parallel.topology.link_cost_matrix); default "
                         "uniform")
    sp.add_argument("--hierarchy", action="store_true",
                    help="also render the two-level (ICI+DCN) "
                         "decomposition: per-split DCN transfers/bytes "
                         "over the unchanged inner plan, plus the "
                         "two-level placement (identity on a uniform "
                         "fabric — rendered as flat-equivalent)")
    sp.add_argument("--hosts", type=int, default=2,
                    help="host count for --hierarchy what-if splits "
                         "when the choice itself is flat (default 2)")
    sp.add_argument("--host-map", default="",
                    help="inline JSON device->host list for --hierarchy "
                         "(e.g. '[0,1,0,1,0,1,0,1]' for an interleaved "
                         "fabric); default: contiguous equal split")
    _add_config_flags(sp)

    sp = sub.add_parser("prune", help="drop entries by filter")
    sp.add_argument("--db", required=True)
    sp.add_argument("--platform", default="")
    sp.add_argument("--source", default="",
                    choices=("",) + plandb.SOURCES)
    sp.add_argument("--older-than-days", type=float, default=None)

    sp = sub.add_parser("seed",
                        help="insert the recorded BASELINE.md verdicts")
    sp.add_argument("--db", required=True)
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--force", action="store_true",
                    help="overwrite existing entries at the seed keys")

    sp = sub.add_parser(
        "calibrate",
        help="fit calibration constants from attribution records and "
             "install them in the DB (jax-free)")
    sp.add_argument("--db", required=True)
    sp.add_argument("--from-metrics", default="",
                    help="metrics JSONL with plan.attrib.phase records "
                         "(a --metrics-out file)")
    sp.add_argument("--from-ledger", default="",
                    help="LEDGER.jsonl with ingested plan.attrib.* "
                         "entries (lower resolution: one trimean per "
                         "run/phase)")
    sp.add_argument("--phase", action="append", default=None,
                    help="fit only samples of this phase (repeatable). "
                         "One phase = one measurement population: probe "
                         "chunks and the epilogue exchange loop amortize "
                         "dispatch overhead differently")
    sp.add_argument("--platform", default="cpu",
                    help="platform key the fitted row serves (autotune "
                         "installs it for matching configs)")
    sp.add_argument("--metrics-out", default="",
                    help="also append a calibration.fitted telemetry "
                         "record here")
    sp.add_argument("--run-id", default="")

    sp = sub.add_parser("calibration",
                        help="show or diff the DB's fitted calibrations "
                             "(jax-free)")
    sp.add_argument("action", choices=("show", "diff"))
    sp.add_argument("--db", required=True)
    sp.add_argument("--platform", default="",
                    help="restrict diff to one platform (default: all)")

    sp = sub.add_parser("autotune", help="tune one config now")
    sp.add_argument("--db", default="")
    sp.add_argument("--cpu", type=int, default=0)
    sp.add_argument("--top-n", type=int, default=3)
    sp.add_argument("--probe-iters", type=int, default=4)
    sp.add_argument("--no-probe", action="store_true",
                    help="static ranking only (no compiles)")
    sp.add_argument("--force", action="store_true",
                    help="re-tune through an existing DB entry")
    sp.add_argument("--methods", default="",
                    help="comma list restricting the searched exchange "
                         "methods (e.g. 'remote-dma' to tune/persist a "
                         "remote-dma-keyed entry); default: all")
    sp.add_argument("--variants", default="",
                    help="comma list restricting the searched kernel "
                         "variants: 'fused' (the fused compute+exchange "
                         "variant), 'persistent' (the whole-chunk "
                         "mega-kernel; needs --ks depths >= 2) and/or "
                         "'none' (the unvariant programs); default: the "
                         "unvariant program + remote-dma's fused variant "
                         "+ (when --ks reaches 2) its persistent "
                         "variant")
    sp.add_argument("--ks", default="1",
                    help="comma list of temporal multistep depths to "
                         "search (deep-halo k; e.g. '1,2,4' lets the "
                         "persistent whole-chunk variant compete)")
    _add_config_flags(sp)
    from ._bench_common import add_metrics_flags

    add_metrics_flags(sp)

    args = p.parse_args(argv)
    return {
        "show": cmd_show,
        "explain": cmd_explain,
        "prune": cmd_prune,
        "seed": cmd_seed,
        "calibrate": cmd_calibrate,
        "calibration": cmd_calibration,
        "autotune": cmd_autotune,
    }[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())
