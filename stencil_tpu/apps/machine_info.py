"""machine-info — print the cluster/device inventory and link matrices.

TPU-native analogue of the reference's machine-info executable
(reference: bin/machine_info.cu:49-75, machine.hpp:106-140): dumps the
Machine model (nodes, processes, devices with ICI coords) plus the
distance and bandwidth matrices the NodeAware placement consumes — the
introspection needed to trust placement on real hardware.

Also prints the default partition the framework would choose for these
devices (NodePartition hosts x devices-per-host), closing the loop from
inventory to decomposition.

``--json`` emits the same inventory machine-readably — one telemetry
record per line in the metrics JSONL schema (stencil_tpu/obs/telemetry.py)
— the analogue of the reference's NVML dump, consumable by the same
tooling as ``--metrics-out`` files (apps/report.py validates it).

Usage: python -m stencil_tpu.apps.machine_info [--cpu 8] [--size 256] [--json]
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

import jax
import numpy as np

from ..geometry import Dim3, NodePartition, Radius
from ..obs import telemetry
from ..parallel.machine import Machine
from ..utils import logging as log


def run(devices=None, size: int = 256, radius: int = 1) -> dict:
    m = Machine.detect(devices)
    n = len(m.devices)
    hosts = max(1, m.process_count)
    part = NodePartition(
        Dim3(size, size, size), Radius.constant(radius), hosts, max(1, n // hosts)
    )
    return {
        "machine": m,
        "dist": m.distance_matrix(),
        "bw": m.bandwidth_matrix(),
        "partition": part.dim(),
        "size": size,
    }


def fabric_fingerprint(machine: Optional[Machine] = None,
                       devices=None) -> dict:
    """The scalar identity of the fabric a measurement ran on: process
    count, host count, device count, platform, and the virtual-host
    override if any. Attribution records (obs/attribution.emit_phase)
    embed these as ``fabric_*`` extras so a fitted calibration row can
    be traced to the fabric whose constants it encodes — a row fitted on
    an 8-device single-host CPU mesh must not silently price a 2-host
    TPU run."""
    import os

    m = machine if machine is not None else Machine.detect(devices)
    platform = m.devices[0].platform if m.devices else "unknown"
    return {
        "processes": int(m.process_count),
        "hosts": int(m.num_nodes()),
        "devices": len(m.devices),
        "platform": str(platform),
        "virtual_hosts": os.environ.get("STENCIL_VIRTUAL_HOSTS", ""),
    }


def report(r: dict) -> str:
    m: Machine = r["machine"]
    with np.printoptions(precision=2, suppress=True, linewidth=200):
        return "\n".join(
            [
                m.summary(),
                f"default partition for {r['size']}^3: {r['partition']} "
                "(hosts x devices/host min-interface split)",
                "distance matrix (hops; self=0.1, remote=7.0):",
                str(r["dist"]),
                "bandwidth matrix (1/distance):",
                str(r["bw"]),
            ]
        )


def emit_records(r: dict, rec: "telemetry.Recorder") -> list:
    """The inventory as telemetry records (one JSON object per line in the
    sink): the machine-readable NVML-dump analogue."""
    m: Machine = r["machine"]
    out = [rec.meta(
        "machine",
        nodes=m.num_nodes(),
        processes=m.process_count,
        devices=len(m.devices),
        hostnames={str(k): v for k, v in sorted(m.hostnames.items())},
    )]
    for d in m.devices:
        out.append(rec.meta(
            "machine.device",
            index=d.index,
            platform=d.platform,
            device_kind=d.kind,
            process=d.process_index,
            coords=list(d.coords) if d.coords is not None else None,
            core_on_chip=d.core_on_chip,
        ))
    out.append(rec.meta("machine.fabric", **fabric_fingerprint(m)))
    part = r["partition"]
    out.append(rec.meta(
        "machine.partition",
        dim=[part.x, part.y, part.z],
        size=r["size"],
    ))
    out.append(rec.meta("machine.distance_matrix",
                        matrix=np.asarray(r["dist"]).tolist()))
    out.append(rec.meta("machine.bandwidth_matrix",
                        matrix=np.asarray(r["bw"]).tolist()))
    return out


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="cluster/device inventory (TPU)")
    p.add_argument("--size", type=int, default=256, help="domain for the partition hint")
    p.add_argument("--radius", type=int, default=1)
    p.add_argument("--cpu", type=int, default=0, help="force N virtual CPU devices")
    p.add_argument("--json", action="store_true",
                   help="emit the inventory as telemetry JSONL on stdout "
                        "(and to --metrics-out when given) instead of text")
    from ._bench_common import add_metrics_flags, start_metrics
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    rec = start_metrics(args, "machine_info")
    r = run(size=args.size, radius=args.radius)
    if args.json:
        stdout_rec = telemetry.Recorder(sink=sys.stdout, app="machine_info",
                                        run_id=rec.run_id)
        emit_records(r, stdout_rec)
        if rec.enabled:
            emit_records(r, rec)
        return 0
    if rec.enabled:
        emit_records(r, rec)
    print(report(r))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
