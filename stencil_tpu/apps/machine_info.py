"""machine-info — print the cluster/device inventory and link matrices.

TPU-native analogue of the reference's machine-info executable
(reference: bin/machine_info.cu:49-75, machine.hpp:106-140): dumps the
Machine model (nodes, processes, devices with ICI coords) plus the
distance and bandwidth matrices the NodeAware placement consumes — the
introspection needed to trust placement on real hardware.

Also prints the default partition the framework would choose for these
devices (NodePartition hosts x devices-per-host), closing the loop from
inventory to decomposition.

Usage: python -m stencil_tpu.apps.machine_info [--cpu 8] [--size 256]
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax
import numpy as np

from ..geometry import Dim3, NodePartition, Radius
from ..parallel.machine import Machine
from ..utils import logging as log


def run(devices=None, size: int = 256, radius: int = 1) -> dict:
    m = Machine.detect(devices)
    n = len(m.devices)
    hosts = max(1, m.process_count)
    part = NodePartition(
        Dim3(size, size, size), Radius.constant(radius), hosts, max(1, n // hosts)
    )
    return {
        "machine": m,
        "dist": m.distance_matrix(),
        "bw": m.bandwidth_matrix(),
        "partition": part.dim(),
        "size": size,
    }


def report(r: dict) -> str:
    m: Machine = r["machine"]
    with np.printoptions(precision=2, suppress=True, linewidth=200):
        return "\n".join(
            [
                m.summary(),
                f"default partition for {r['size']}^3: {r['partition']} "
                "(hosts x devices/host min-interface split)",
                "distance matrix (hops; self=0.1, remote=7.0):",
                str(r["dist"]),
                "bandwidth matrix (1/distance):",
                str(r["bw"]),
            ]
        )


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="cluster/device inventory (TPU)")
    p.add_argument("--size", type=int, default=256, help="domain for the partition hint")
    p.add_argument("--radius", type=int, default=1)
    p.add_argument("--cpu", type=int, default=0, help="force N virtual CPU devices")
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    r = run(size=args.size, radius=args.radius)
    print(report(r))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
