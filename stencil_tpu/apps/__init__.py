"""CLI applications — the TPU-native counterparts of the reference's
``bin/`` executables (reference: bin/CMakeLists.txt:99-151). Each app
prints one CSV result row matching the reference's format."""
