"""jacobi3d — 7-point Jacobi heat diffusion, weak-scaled.

TPU-native port of the reference's main demo app (reference:
bin/jacobi3d.cu): a hot and a cold sphere fixed in a periodic box, 6-neighbor
averaging, interior/exterior comm overlap, optional ParaView CSV dumps, and
a one-line CSV result:

  jacobi3d,<method>,<processes>,<devices>,<x>,<y>,<z>,<exchBytes>,<minIter>,<trimeanIter>

(reference prints per-method byte columns, bin/jacobi3d.cu:386-391; here the
single collective transport's logical bytes are printed once.)

Usage: python -m stencil_tpu.apps.jacobi3d --x 512 --y 512 --z 512 --iters 5
"""

from __future__ import annotations

import argparse
import os
import time
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..api import DistributedDomain
from ..geometry import Dim3, prime_factors
from ..obs import telemetry
from ..ops.jacobi import INIT_TEMP, make_jacobi_loop, make_jacobi_step, sphere_sel
from ..utils import timer
from ..parallel import Method
from ..parallel.exchange import shard_blocks
from ..utils.statistics import Statistics
from ..utils.sync import hard_sync
from ..utils import logging as log


def weak_scale(x: int, y: int, z: int, num_subdomains: int) -> Dim3:
    """Grow the domain to keep points/subdomain constant: multiply prime
    factors of N into the smallest axis (reference: bin/jacobi3d.cu:190-205)."""
    for pf in prime_factors(num_subdomains):
        if x <= y and x <= z:
            x *= pf
        elif y <= z:
            y *= pf
        else:
            z *= pf
    return Dim3(x, y, z)


def run(
    x: int,
    y: int,
    z: int,
    iters: int = 5,
    overlap: bool = True,
    method: Method = Method.AXIS_COMPOSED,
    devices=None,
    weak: bool = True,
    paraview: bool = False,
    paraview_every: int = -1,
    prefix: str = "",
    partition=None,
    warmup: int = 1,
    chunk: Optional[int] = None,
    deep_halo: int = 1,
    multistep_rows: Optional[int] = None,
    metrics_dma: bool = False,
    ckpt_dir: Optional[str] = None,
    ckpt_every: int = 0,
    ckpt_keep: int = 3,
    resume: bool = False,
    autotune: bool = False,
    plan_db: Optional[str] = None,
    health_every: int = 0,
    max_abs: Optional[float] = None,
    max_rollbacks: int = 3,
    rollback_backoff: float = 0.25,
    inject: Optional[str] = None,
    wire_dtype: Optional[str] = None,
    fused: bool = False,
    kernel_variant: Optional[str] = None,
    sentinel=None,
    status=None,
    replan: bool = False,
    replan_probe: bool = False,
) -> dict:
    # kernel_variant is the tuned-plan vocabulary ("fused" / "persistent",
    # plan/ir.py); --fused stays as the historical spelling of the former
    if fused and kernel_variant is None:
        kernel_variant = "fused"
    if kernel_variant == "fused":
        fused = True
    elif kernel_variant == "persistent" and deep_halo < 2:
        raise ValueError(
            "kernel_variant='persistent' is the whole-chunk temporal "
            "fusion: it needs --deep-halo >= 2 (the chunk depth k; the "
            "domain realizes radius*k halos)")
    elif kernel_variant not in (None, "fused", "persistent"):
        raise ValueError(
            f"unknown kernel_variant {kernel_variant!r}: valid values are "
            "'fused' and 'persistent'")
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if (weak and n > 1 and partition is None
            and x % 128 == 0
            and all(d.platform == "tpu" for d in devices)):
        # TPU-first weak scaling: grow + split over z/y only
        # (geometry.decompose_zy) so every chip keeps the tight-x layout
        # and the mesh is a 2D ICI-friendly z x y grid; the reference's
        # smallest-axis weak_scale + 3-axis partition stays for CPU and
        # explicit partitions
        from ..geometry import decompose_zy

        d3 = decompose_zy(n)
        size = Dim3(x, y * d3.y, z * d3.z)
        partition = d3
    else:
        size = weak_scale(x, y, z, n) if weak else Dim3(x, y, z)

    dd = DistributedDomain(size.x, size.y, size.z)
    # deep_halo > 1 realizes radius-k halos so the fused loop can take the
    # communication-avoiding multistep on multi-block meshes (one radius-k
    # exchange per k steps); the workload stays radius-1 jacobi
    tight_x = False
    pdim = None
    if partition is not None:
        pdim = Dim3.of(partition)
    elif n == 1:
        pdim = Dim3(1, 1, 1)
    if (pdim is not None and pdim.x == 1 and pdim.flatten() == n
            and size.x % 128 == 0
            and size.y % pdim.y == 0 and size.z % pdim.z == 0
            # no in-kernel x wrap in the global AUTO_SPMD program, and the
            # REMOTE_DMA carrier/emulation assumes inline halos everywhere
            and method not in (Method.AUTO_SPMD, Method.REMOTE_DMA)
            and not autotune  # the tuner may pick AUTO_SPMD, which cannot
                              # run the tight-x no-x-halo layout
            and all(d.platform == "tpu" for d in devices)):
        # tight-x layout: a single-BLOCK x axis wraps x in-kernel (lane
        # rolls), so no x halo columns are allocated — every slab DMA
        # sheds the px/nx lane padding (1.36x at 512^3, BASELINE.md round
        # 3). Multi-block y/z axes keep their inline halos and exchange
        # normally; their overlap shells take the roll-aware sweep. An
        # x-split, uneven, or oversubscribed partition keeps inline halos
        # everywhere (the Pallas fast path disengages there).
        from ..geometry import Radius

        dd.set_radius(Radius.constant(deep_halo).without_x())
        tight_x = True
    else:
        dd.set_radius(deep_halo)
    dd.set_methods(method)
    dd.set_devices(devices)
    if fused:
        # the fused compute+exchange variant (REMOTE_DMA only —
        # DistributedDomain validates loudly at realize())
        dd.set_fused_exchange(True)
    if kernel_variant == "persistent":
        # the persistent whole-chunk variant (REMOTE_DMA only — realize()
        # raises loudly otherwise): one radius*k exchange per k-step
        # chunk, k = deep_halo (the radius the domain realized above)
        dd.set_persistent_exchange(True)
    if wire_dtype:
        dd.set_wire_dtype(wire_dtype)
    if partition is not None:
        dd.set_partition(partition)
    if autotune:
        # plan/ subsystem: choose (partition x method x batching) from the
        # DB or by static-rank + measured probes; an explicit --partition
        # or tight-x radius pin above still wins (realize() warns)
        dd.enable_autotune(db_path=plan_db)
    h = dd.add_data("temperature", "float32")
    dd.realize()
    if autotune:
        method = dd._method  # the tuned method labels the CSV row

    # init: uniform lukewarm field (reference: bin/jacobi3d.cu:18-27)
    rec = telemetry.get()
    with rec.span("jacobi.init", phase="init"):
        sharding = dd.sharding()
        shape = dd.spec.stacked_shape_zyx()
        dd.set_curr(h, jax.device_put(jnp.full(shape, INIT_TEMP, jnp.float32), sharding))
        sel = shard_blocks(sphere_sel(size), dd.spec, dd.mesh)

    if paraview:
        dd.write_paraview(prefix + "jacobi3d_init")

    # checkpoint/restart (ckpt/): resume replaces the fresh init with the
    # newest durable snapshot's state — elastically, so a run revived on a
    # different partition/device count continues the same campaign
    start = 0
    if ckpt_dir and resume:
        from ._bench_common import resume_from_checkpoint

        start = resume_from_checkpoint(dd, ckpt_dir, iters)
    kill_after = int(os.environ.get("STENCIL_CKPT_KILL_AFTER_SAVE", "-1") or -1)

    def save_ckpt(step: int, state) -> None:
        dd.set_curr(h, state)
        dd.save_checkpoint(ckpt_dir, step, keep=ckpt_keep)
        if 0 <= kill_after <= step:
            # injected-kill hook (CI checkpoint gate / tests): die hard
            # right after this snapshot is durable — the revival must
            # continue from it, not from step 0
            dd.finish_checkpoints()
            log.warn(f"STENCIL_CKPT_KILL_AFTER_SAVE: dying after step {step}")
            os._exit(17)

    curr, nxt = dd.get_curr(h), dd.get_next(h)
    stepwise = paraview and paraview_every > 0
    if chunk is None:
        chunk = 1 if stepwise else min(iters, 10)
    chunk = min(chunk, iters)

    loops = {}  # iters-per-call -> compiled fn

    def get_loop(k: int):
        if k not in loops:
            # an explicit deep_halo pins the temporal depth at k=deep_halo on
            # EVERY device count — a single-block run would otherwise take
            # the full default depth (no radius bound) and poison weak-scaling
            # columns against radius-capped N-chip runs (ADVICE r3)
            tk = deep_halo if deep_halo >= 2 else None
            # the persistent chunk driver owns ALL call sizes (a 1-iter
            # call is its depth-1 tail chunk); make_jacobi_step has no
            # chunk schedule
            loops[k] = (
                make_jacobi_loop(dd.halo_exchange, k, overlap=overlap,
                                 temporal_k=tk,
                                 multistep_rows=multistep_rows)
                if k > 1 or kernel_variant == "persistent"
                else make_jacobi_step(dd.halo_exchange, overlap=overlap)
            )
        return loops[k]

    # Self-healing layer (fault/): the periodic fused health check, the
    # injection schedule, and the rollback policy the guarded loop runs
    # under. All default OFF — the step-loop programs are identical
    # either way (the guard is a separate compiled reduction; pinned by
    # tests/test_fault_health.py).
    from ..fault import (FaultPlan, HealthGuard, RecoveryPolicy, chunk_plan,
                         run_guarded)

    guard = (HealthGuard(every=health_every, max_abs=max_abs)
             if health_every > 0 else None)
    injector = FaultPlan.from_spec(inject)

    # The exact fused-chunk sizes the measured loop will dispatch
    # (checkpoint / health-check boundaries clamp them; injections land
    # at their exact step): ONE schedule drives both warmup and the timed
    # loop, so warmup compiles precisely what runs and no XLA compile can
    # land inside a timed region.
    def plan_fn(s: int):
        return chunk_plan(
            s, iters, chunk,
            every=(ckpt_every if (ckpt_dir and ckpt_every > 0) else 0,
                   health_every if guard is not None else 0),
            at=injector.steps() if injector is not None else (),
        )

    plan = plan_fn(start)

    with rec.span("jacobi.warmup", phase="compile", iters=warmup * chunk):
        if ckpt_dir:
            # checkpointed runs are step-exact by contract (save at k,
            # resume, continue to n == uninterrupted n): warm the compile
            # caches on throwaway copies so warmup never advances the
            # state (the loops donate their inputs, so fresh buffers are
            # needed anyway) — one throwaway call per distinct chunk size
            # in the plan
            if warmup:
                for k in dict.fromkeys(plan):
                    get_loop(k)(curr + 0, nxt + 0, sel)
                hard_sync(curr)
        else:
            # benchmark path: warmup ADVANCES the state (content is
            # irrelevant without checkpoints), so only the main chunk
            # size is warmed — tail/boundary sizes compile in the timed
            # region exactly as they always did
            loop = get_loop(chunk)
            for _ in range(warmup):  # compile + warm caches, excluded from timing
                curr, nxt = loop(curr, nxt, sel)
            if warmup:
                hard_sync(curr)

    # Iterations run in fused chunks: one dispatch + one hard sync per chunk
    # (block_until_ready is unreliable and per-call dispatch is ~0.7 s on the
    # tunneled TPU platform — see utils/sync.py). The per-iteration statistic
    # is each chunk's mean, trimean'd over chunks like the reference's
    # per-iter times (bin/jacobi3d.cu:370-372). A short final chunk keeps the
    # total at exactly `iters`. The loop itself runs under the fault/
    # recovery engine: per chunk, step -> inject -> health check ->
    # checkpoint (the check precedes the save, so a poisoned state is
    # never persisted), and a NumericalFault rolls back to the newest
    # valid snapshot with exponential backoff.
    iter_time = Statistics()

    def step_fn(st, k):
        nonlocal nxt
        c, n2 = get_loop(k)(st["temperature"], nxt, sel)
        hard_sync(c)
        nxt = n2
        return {"temperature": c}

    def on_chunk(st, k, per, done_now):
        iter_time.insert(per)
        rec.emit("span", "jacobi.iter", phase="step", seconds=per, iters=k)
        if stepwise and done_now % paraview_every == 0:
            dd.set_curr(h, st["temperature"])
            dd.write_paraview(f"{prefix}jacobi3d_{done_now}")

    save_fn = restore_fn = quarantine_fn = flush_fn = None
    if ckpt_dir:
        if ckpt_every > 0:
            save_fn = lambda s, st: save_ckpt(s, st["temperature"])  # noqa: E731
        flush_fn = dd.flush_checkpoints

        def restore_fn():
            s = dd.restore_checkpoint(ckpt_dir)
            if s is None:
                return None
            return s, {"temperature": dd.get_curr(h)}

        def quarantine_fn(s):
            from ..ckpt import quarantine_snapshot, snapshot_name

            quarantine_snapshot(ckpt_dir, snapshot_name(s),
                                reason="restored state failed health check")

    # The mid-run plan hot-swap (ROADMAP #6, the half PR 12's sentinel
    # was waiting for): when the live sentinel fires replan.requested,
    # the controller re-probes the autotuner between chunks and installs
    # the winning compiled plan via DistributedDomain.replan — the
    # in-memory elastic reshard, bit-identical by construction. Needs the
    # sentinel (the trigger) and a full-radius layout (the tight-x pin
    # realizes no x halos, which only the pinned partition can run).
    controller = None
    if replan and sentinel is None:
        log.warn("--replan needs --live-sentinel (replan.requested is "
                 "the trigger); ignoring")
    elif replan and tight_x:
        log.warn("--replan is unavailable under the tight-x no-x-halo "
                 "layout (a retuned x-split partition could not realize "
                 "it); ignoring")
    elif replan:
        from ..parallel.topology import link_cost_matrix
        from ..plan.ir import PlanChoice, PlanConfig
        from ..plan.replan import ReplanController

        def retune_fn():
            from ..plan.autotune import autotune as _plan_autotune

            res = _plan_autotune(
                dd.size, dd.radius, list(dd._dtypes), devices=devices,
                db_path=plan_db, probe=replan_probe, force=True,
            )
            return res.choice

        def apply_replan(choice, st):
            nonlocal sel, nxt
            dd.set_curr(h, st["temperature"])
            dd.replan(choice)
            loops.clear()  # the old plan's compiled loops are stale
            sel = shard_blocks(sphere_sel(size), dd.spec, dd.mesh)
            nxt = dd.get_next(h)
            return {"temperature": dd.get_curr(h)}

        controller = ReplanController(
            retune_fn, apply_replan, sentinel=sentinel,
            current_choice=PlanChoice.from_json(dd.plan_meta()["choice"]),
            config=PlanConfig.make(dd.size, dd.radius, list(dd._dtypes),
                                   n, devices[0].platform),
            link_costs=link_cost_matrix(devices),
        )
        sentinel.on_replan = controller.request

    loop_t0 = time.perf_counter()
    state, done = run_guarded(
        {"temperature": curr},
        start=start, iters=iters, plan_fn=plan_fn, step_fn=step_fn,
        guard=guard, injector=injector,
        policy=RecoveryPolicy(max_rollbacks=max_rollbacks,
                              backoff_s=rollback_backoff),
        save_fn=save_fn, ckpt_every=ckpt_every, restore_fn=restore_fn,
        quarantine_fn=quarantine_fn, flush_fn=flush_fn, on_chunk=on_chunk,
        spec=dd.spec, ckpt_dir=ckpt_dir, app="jacobi3d",
        sentinel=sentinel, status=status, replan=controller,
    )
    # whole-loop wall clock, INCLUDING what the per-chunk spans exclude
    # (health checks, checkpoint saves, injected faults, backoff and
    # rollback recovery) — the ledger gate's wall-level regression leg
    # (scripts/ci_perf_gate.py trips it with an injected slow: fault)
    loop_wall_s = time.perf_counter() - loop_t0
    curr = state["temperature"]
    if controller is not None and controller.swaps:
        # the CSV row and byte accounting must describe the plan that
        # FINISHED the run, not the one it started on
        method = dd._method
    if ckpt_dir:
        if done > start or start == 0:
            # the final state is always durable (step == iters), so a
            # revived campaign that already finished resumes directly to
            # the report
            save_ckpt(iters, curr)
        # resumed past the end without stepping: the durable snapshot
        # already covers (and may EXCEED) this run's target — re-labeling
        # it as step `iters` would corrupt the campaign's step accounting
        dd.finish_checkpoints()
    if rec.enabled:
        # per-phase split + the compiled programs' static truth. The step
        # fuses exchange+compute, so the exchange share is measured as a
        # standalone fused loop on the same state (halo exchange is
        # idempotent on exchanged data — the astaroth exchElapsed idiom);
        # the census pins the exact on-wire bytes of one exchange.
        itemsizes = [jnp.dtype(jnp.float32).itemsize]
        telemetry.record_exchange_truth(
            dd.halo_exchange, {h.idx: curr}, itemsizes)
        n_ex = max(1, min(chunk, 10))
        exch_loop = dd.halo_exchange.make_loop(n_ex)
        st = {h.idx: curr}
        with rec.span("jacobi.exchange_warmup", phase="compile"):
            st = exch_loop(st)
            hard_sync(st)
        # slow@ injections scheduled PAST the step loop land inside the
        # timed exchange window below (steps iters+1..iters+3, one per
        # sample): `--inject slow@{iters+k}:seconds=S` inflates exactly
        # one measured sample — the drift sentinel's trip-proof knob
        # (scripts/ci_attrib_gate.py). Only slow faults fire here; state
        # corruption stays confined to the guarded step loop.
        slow_tail = None
        if injector is not None:
            tail = [i for i in injector.injections
                    if i.kind == "slow" and i.step > iters]
            if tail:
                slow_tail = FaultPlan(tail, seed=injector.seed)
        exch_samples = []
        for i in range(3):
            t0 = time.perf_counter()
            st = exch_loop(st)
            if slow_tail is not None:
                st = slow_tail.fire_due(st, iters + i, iters + i + 1)
            hard_sync(st)
            per = (time.perf_counter() - t0) / n_ex
            exch_samples.append(per)
            rec.emit("span", "jacobi.exchange", phase="exchange",
                     seconds=per, iters=n_ex)
        curr = st[h.idx]
        # per-phase attribution: pair the cost model's prediction for the
        # realized plan with the measured exchange share — the autotuner's
        # calibration (fitted, when the plan DB carries one) prices it, so
        # the records judge the constants that actually ranked this plan
        from ..obs import attribution
        from ..plan.ir import PlanChoice, PlanConfig
        from .machine_info import fabric_fingerprint

        pm = dd.plan_meta()
        plan_choice = PlanChoice.from_json(pm["choice"])
        tuned = dd.autotune_result
        attribution.attribute_and_judge(
            rec, PlanConfig.from_json(pm["key"]), plan_choice,
            exch_samples, phase="jacobi.exchange",
            calibration=tuned.calibration if tuned is not None else None,
            kernel_variant=plan_choice.kernel_variant,
            fabric=fabric_fingerprint(devices=devices))
        # the run's plan identity: which exact PlanChoice produced these
        # numbers, under which calibration — the join key between a
        # metrics file, the plan DB, and a fitted calibration row
        rec.meta("plan.fingerprint",
                 fingerprint=plan_choice.fingerprint(),
                 choice=plan_choice.label(),
                 calibration=(tuned.calibration_provenance
                              if tuned is not None else "modeled(default)"))
        if metrics_dma:
            # static per-kernel HBM DMA truth from the compiled Mosaic
            # artifact (utils/mosaic_traffic) — only meaningful where the
            # Pallas fast path engages (a TPU-lowered kernel exists)
            from ..ops.jacobi import _want_pallas

            if _want_pallas(dd.halo_exchange, None):
                # rebuild EXACTLY the measured configuration (same temporal
                # depth pin as get_loop) — the DMA truth must describe the
                # kernel that actually ran
                telemetry.record_dma_traffic(
                    lambda: (
                        make_jacobi_loop(
                            dd.halo_exchange, chunk, overlap=overlap,
                            use_pallas=True,
                            temporal_k=deep_halo if deep_halo >= 2 else None,
                            multistep_rows=multistep_rows),
                        (curr, nxt, sel),
                    ),
                )
            else:
                rec.meta("dma.skipped",
                         reason="pallas fast path not engaged")
    dd.set_curr(h, curr)
    dd.set_next(h, nxt)

    if paraview:
        dd.write_paraview(prefix + "jacobi3d_final")

    cells = size.flatten()
    if iter_time.count() == 0:
        # resumed at/past the target step: nothing left to time (the inf
        # placeholder keeps downstream ratios at 0, and gauges that would
        # serialize as non-strict JSON are skipped below)
        log.info(f"resume found step {start} >= iters {iters}; no timed work")
        iter_time.insert(float("inf"))
    trimean = iter_time.trimean()
    result = {
        "app": "jacobi3d",
        "method": method.value,
        "processes": jax.process_count(),
        "devices": n,
        "x": size.x,
        "y": size.y,
        "z": size.z,
        "exchange_bytes": dd.exchange_bytes_for_method(method),
        "iter_min_s": iter_time.min(),
        "iter_trimean_s": trimean,
        "mcells_per_s": cells / trimean / 1e6,
        "mcells_per_s_per_dev": cells / trimean / 1e6 / n,
        "overlap": overlap,
        "domain": dd,
        "handle": h,
    }
    if rec.enabled:
        rec.gauge("jacobi.loop_wall_s", loop_wall_s, phase="step", unit="s")
        rec.gauge("jacobi.mcells_per_s", result["mcells_per_s"], phase="step")
        rec.gauge("jacobi.mcells_per_s_per_dev",
                  result["mcells_per_s_per_dev"], phase="step")
        if np.isfinite(trimean):  # inf would serialize as non-strict JSON
            rec.gauge("jacobi.iter_trimean_s", trimean, phase="step",
                      unit="s")
        rec.counter("jacobi.exchange_bytes", bytes=result["exchange_bytes"],
                    phase="exchange", method=method.value)
    return result


def csv_row(r: dict) -> str:
    return (
        f"jacobi3d,{r['method']},{r['processes']},{r['devices']},"
        f"{r['x']},{r['y']},{r['z']},{r['exchange_bytes']},"
        f"{r['iter_min_s']:.6f},{r['iter_trimean_s']:.6f}"
    )


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="3D Jacobi heat diffusion (TPU)")
    p.add_argument("--x", type=int, default=512)
    p.add_argument("--y", type=int, default=512)
    p.add_argument("--z", type=int, default=512)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--no-overlap", action="store_true", help="disable interior/exterior overlap")
    p.add_argument("--direct26", action="store_true", help="use 26 per-direction permutes")
    p.add_argument("--method", choices=[m.value for m in Method], default=None,
                   help="exchange strategy (auto-spmd lets the SPMD "
                        "partitioner synthesize the halo collectives; "
                        "overrides --direct26)")
    p.add_argument("--no-weak", action="store_true", help="fixed total domain (strong)")
    p.add_argument("--paraview", action="store_true")
    p.add_argument("--paraview-every", type=int, default=-1,
                   help="with --paraview, also dump every N iterations")
    p.add_argument("--checkpoint-period", type=int, default=None,
                   help="DEPRECATED alias of --paraview-every (it was always "
                        "a ParaView dump cadence; real checkpointing is "
                        "--ckpt-dir/--ckpt-every)")
    p.add_argument("--ckpt-dir", type=str, default="",
                   help="write elastic checkpoint snapshots here (ckpt/ "
                        "subsystem: sharded npz + manifest, crash-safe)")
    p.add_argument("--ckpt-every", type=int, default=0,
                   help="checkpoint every N iterations (0 = only the final "
                        "state; needs --ckpt-dir)")
    p.add_argument("--ckpt-keep", type=int, default=3,
                   help="retention: keep the newest N snapshots")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest valid snapshot under "
                        "--ckpt-dir when one exists (fresh start otherwise)")
    p.add_argument("--health-every", type=int, default=0,
                   help="numerical health guard (fault/): one fused "
                        "isfinite reduction over the state every N steps; "
                        "a fault rolls back to the newest valid snapshot "
                        "(0 = off; the step-loop HLO is unchanged)")
    p.add_argument("--max-abs", type=float, default=0.0,
                   help="with --health-every, also fault when any "
                        "quantity's max|u| exceeds this divergence "
                        "ceiling (0 = no ceiling)")
    p.add_argument("--max-rollbacks", type=int, default=3,
                   help="rollbacks allowed per faulting step before the "
                        "run aborts with rc 43 + a fault-evidence.json "
                        "bundle")
    p.add_argument("--rollback-backoff", type=float, default=0.25,
                   help="first-retry backoff seconds (doubles per repeated "
                        "fault at the same step)")
    p.add_argument("--inject", type=str, default="",
                   help="deterministic fault injection spec, e.g. "
                        "'nan@3,crash@5:rc=7' (see fault/inject.py; "
                        "default: the STENCIL_FAULT_INJECT env var)")
    p.add_argument("--autotune", action="store_true",
                   help="choose the exchange plan (partition x method x "
                        "quantity batching) via the plan/ autotuner: plan-DB "
                        "hit replays with zero probes, miss static-ranks + "
                        "probes and persists the winner to --plan-db")
    p.add_argument("--plan-db", type=str, default="",
                   help="on-disk plan DB (JSON) for --autotune; also "
                        "inspectable via apps/plan_tool.py")
    p.add_argument("--replan", action="store_true",
                   help="mid-run plan hot-swap (needs --live-sentinel): "
                        "on replan.requested the autotuner re-tunes "
                        "between chunks and the winning compiled plan is "
                        "installed in place (replan.applied/rejected in "
                        "the metrics; state is bit-identical across the "
                        "swap)")
    p.add_argument("--replan-probe", action="store_true",
                   help="with --replan, refine the re-tune with measured "
                        "probes (default: static ranking only, so the "
                        "swap stays cheap)")
    p.add_argument("--wire-dtype", type=str, default="",
                   help="on-the-wire halo compression (bfloat16 or the fp8 "
                        "tier float8_e4m3fn): wire-crossing "
                        "exchange carriers narrow to this dtype (LOSSY — "
                        "halos round to the wire precision; "
                        "bench_exchange --wire-ab measures the error)")
    p.add_argument("--fused", action="store_true",
                   help="the fused compute+exchange variant of "
                        "--method remote-dma: every per-direction copy "
                        "starts boundary-first and interior compute hides "
                        "the wire (ops/fused_stencil.py; "
                        "fused.overlap_fraction in the metrics)")
    p.add_argument("--kernel-variant", choices=["fused", "persistent"],
                   default=None,
                   help="REMOTE_DMA kernel variant (plan/ir.py vocabulary; "
                        "an unknown value is rejected here, naming this "
                        "set): 'fused' = the --fused overlap kernel; "
                        "'persistent' = the whole-chunk temporal fusion "
                        "(ops/persistent_stencil.py) — one radius*k "
                        "exchange per k-step chunk with k = --deep-halo "
                        "(>= 2 required), launch count O(chunks) not "
                        "O(steps)")
    p.add_argument("--prefix", type=str, default="")
    p.add_argument("--cpu", type=int, default=0, help="force N virtual CPU devices")
    p.add_argument("--virtual-hosts", type=int, default=0,
                   help="emulate N hosts over the local device list "
                        "(sets STENCIL_VIRTUAL_HOSTS: id-sorted "
                        "contiguous groups) — opens the hierarchical "
                        "ICI+DCN plan dimension to --autotune/--plan-db "
                        "without a multi-process fabric")
    p.add_argument("--deep-halo", type=int, default=1,
                   help="realize radius-K halos so the fused loop advances K "
                        "steps per exchange on multi-block meshes "
                        "(communication-avoiding temporal blocking)")
    p.add_argument("--multistep-rows", type=int, default=None,
                   help="force the temporal multistep's row-strip height "
                        "(default: automatic — full planes while they reach "
                        "the depth cap, row-tiled staging beyond; the "
                        "probing knob for the 768^3 depth regime)")
    from ._bench_common import (add_live_flags, add_metrics_flags,
                                canonicalize_live_config, finish_live,
                                make_live, start_metrics)
    add_metrics_flags(p, dma=True)
    add_live_flags(p)
    args = p.parse_args(argv)
    if args.fused and args.kernel_variant == "persistent":
        p.error("--fused conflicts with --kernel-variant persistent "
                "(mutually exclusive kernel variants)")
    try:
        canonicalize_live_config(args)
    except (OSError, ValueError) as e:
        p.error(f"bad --live-config: {e}")

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        # must happen before backend init to actually create N devices
        jax.config.update("jax_num_cpu_devices", args.cpu)
    if args.virtual_hosts:
        os.environ["STENCIL_VIRTUAL_HOSTS"] = str(args.virtual_hosts)
    rec = start_metrics(args, "jacobi3d")
    sentinel, status = make_live(args, rec, "jacobi3d")

    paraview_every = args.paraview_every
    if args.checkpoint_period is not None:
        log.warn("--checkpoint-period is deprecated (it names a ParaView "
                 "dump cadence, not a checkpoint): use --paraview-every; "
                 "checkpoints are --ckpt-dir/--ckpt-every")
        if paraview_every < 0:
            paraview_every = args.checkpoint_period

    from ..fault import FAULT_RC, RecoveryExhausted

    try:
        r = run(
            args.x,
            args.y,
            args.z,
            iters=args.iters,
            overlap=not args.no_overlap,
            method=Method(args.method) if args.method
            else (Method.DIRECT26 if args.direct26 else Method.AXIS_COMPOSED),
            devices=jax.devices()[: args.cpu] if args.cpu else None,
            weak=not args.no_weak,
            paraview=args.paraview,
            paraview_every=paraview_every,
            prefix=args.prefix,
            deep_halo=args.deep_halo,
            multistep_rows=args.multistep_rows,
            metrics_dma=args.metrics_dma and rec.enabled,
            ckpt_dir=args.ckpt_dir or None,
            ckpt_every=args.ckpt_every,
            ckpt_keep=args.ckpt_keep,
            resume=args.resume,
            autotune=args.autotune,
            plan_db=args.plan_db or None,
            health_every=args.health_every,
            max_abs=args.max_abs or None,
            max_rollbacks=args.max_rollbacks,
            rollback_backoff=args.rollback_backoff,
            inject=args.inject or None,
            wire_dtype=args.wire_dtype or None,
            fused=args.fused,
            kernel_variant=args.kernel_variant,
            sentinel=sentinel,
            status=status,
            replan=args.replan,
            replan_probe=args.replan_probe,
        )
    except RecoveryExhausted as e:
        # the loud-degrade contract: evidence bundle on disk, the distinct
        # rc for the watchdog/bench ladder, metrics flushed for archiving
        log.error(f"jacobi3d: {e}")
        finish_live(rec, sentinel, status, outcome="fault")
        if rec.enabled:
            rec.record_timer_buckets()
            rec.close()
        return FAULT_RC
    finish_live(rec, sentinel, status, outcome="done")
    print(csv_row(r))
    log.info(f"mcells/s = {r['mcells_per_s']:.1f} ({r['mcells_per_s_per_dev']:.1f}/device)")
    log.info(timer.report())
    if rec.enabled:
        rec.record_timer_buckets()
        rec.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
