"""bench-exchange — radius-shape sweep of the halo exchange.

TPU-native port of the reference sweep (reference: bin/bench_exchange.cu):
five radius shapes (+x-leaning, x-only, faces-only, face+edge, uniform) at a
fixed per-run extent, reporting trimean seconds and aggregate B/s.

``compare_methods`` additionally rows out AXIS_COMPOSED vs DIRECT26 on the
uniform shape — the data-movement-strategy ablation that stands in for the
reference's bench-mpi-pack pack-kernel-vs-derived-datatype comparison
(reference: bin/bench_mpi_pack.cu:18-80): composed full-extent slabs (6
collectives) against exact-extent per-direction messages (26 collectives).

Usage: python -m stencil_tpu.apps.bench_exchange --x 256 --y 256 --z 256 --iters 30
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax

from ..geometry import Dim3, Radius
from ..parallel import Method
from ._bench_common import time_exchange


def sweep_radii(face: int = 2, edge: int = 1):
    """The five shapes of the reference sweep (bin/bench_exchange.cu:126-195)."""
    px = Radius.constant(0)
    px.set_dir((1, 0, 0), face)

    x_only = Radius.constant(0)
    x_only.set_dir((1, 0, 0), face)
    x_only.set_dir((-1, 0, 0), face)

    faces = Radius.constant(0)
    faces.set_face(face)

    face_edge = Radius.constant(face)
    face_edge.set_corner(edge)

    uniform = Radius.constant(2)
    return [
        (f"px/{face}", px),
        (f"x/{face}", x_only),
        (f"faces/{face}", faces),
        (f"face&edge/{face}/{edge}", face_edge),
        ("uniform/2", uniform),
    ]


def run(x, y, z, iters=30, quantities=4, devices=None, method=Method.AXIS_COMPOSED,
        chunk=10):
    devices = list(devices) if devices is not None else jax.devices()
    rows = []
    for name, radius in sweep_radii():
        r = time_exchange(
            Dim3(x, y, z), radius, iters, method=method, devices=devices,
            quantities=quantities, chunk=chunk,
        )
        rows.append(
            {
                "config": f"{x}-{y}-{z}/{name}",
                "bytes": r["bytes_logical"],
                "trimean_s": r["trimean_s"],
                "bytes_per_s": r["bytes_logical"] / r["trimean_s"],
            }
        )
    return rows


def compare_methods(x, y, z, iters=30, quantities=4, devices=None, radius=2):
    """AXIS_COMPOSED vs DIRECT26 at a uniform radius — the pack-strategy
    ablation (see module docstring). Requires a partition that divides the
    extents evenly (DIRECT26's uniform-blocks constraint)."""
    devices = list(devices) if devices is not None else jax.devices()
    rows = []
    for method in (Method.AXIS_COMPOSED, Method.DIRECT26):
        try:
            r = time_exchange(
                Dim3(x, y, z), Radius.constant(radius), iters, method=method,
                devices=devices, quantities=quantities,
            )
        except ValueError as e:
            # DIRECT26 requires uniform blocks; whether the realized
            # partition (NodePartition inside realize()) divides the
            # extents evenly is its call — report the skip instead of
            # crashing after the main sweep
            print(f"# skipping {method.value}: {e}")
            continue
        rows.append(
            {
                "config": f"{x}-{y}-{z}/method={method.value}",
                "bytes": r["bytes_logical"],
                "trimean_s": r["trimean_s"],
                "bytes_per_s": r["bytes_logical"] / r["trimean_s"],
            }
        )
    return rows


def report_header() -> str:
    return "config,bytes,trimean (s),B/s"


def report_row(row: dict) -> str:
    return f"{row['config']},{row['bytes']},{row['trimean_s']:e},{row['bytes_per_s']:e}"


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="halo exchange radius-shape sweep")
    p.add_argument("--x", type=int, default=256)
    p.add_argument("--y", type=int, default=256)
    p.add_argument("--z", type=int, default=256)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--methods", action="store_true",
                   help="also compare AXIS_COMPOSED vs DIRECT26 (pack ablation)")
    p.add_argument("--cpu", type=int, default=0)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    print(report_header())
    for row in run(args.x, args.y, args.z, iters=args.iters):
        print(report_row(row))
    if args.methods:
        for row in compare_methods(args.x, args.y, args.z, iters=args.iters):
            print(report_row(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
