"""bench-exchange — radius-shape sweep + method ablation of the halo exchange.

TPU-native port of the reference sweep (reference: bin/bench_exchange.cu):
five radius shapes (+x-leaning, x-only, faces-only, face+edge, uniform) at a
fixed per-run extent, reporting trimean seconds and aggregate B/s.

``compare_methods``/``ablate`` row out the three exchange strategies on the
uniform shape — the data-movement-strategy ablation that stands in for the
reference's bench-mpi-pack pack-kernel-vs-derived-datatype comparison
(reference: bin/bench_mpi_pack.cu:18-80): composed full-extent slabs (6
hand-written collectives) vs exact-extent per-direction messages (26) vs
``auto-spmd``, where the SPMD partitioner synthesizes the collectives from
a globally-sharded shifted-slice program. ``--ablate`` additionally pulls
each compiled program's collective census (op counts + interconnect bytes,
utils/hlo_check.collective_census) and asserts all three methods produce
bit-identical halos — the CI gate for the strategy family.

Usage: python -m stencil_tpu.apps.bench_exchange --x 256 --y 256 --z 256 --iters 30
       python -m stencil_tpu.apps.bench_exchange --cpu 8 --ablate
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..geometry import Dim3, Radius
from ..obs import telemetry
from ..parallel import Method
from ._bench_common import (
    add_metrics_flags, coord_state, start_metrics, time_exchange,
)

# ablation order: manual composed, manual direct, partitioner-synthesized,
# kernel-initiated (remote DMA — 0 ppermutes; CPU runs the emulation)
ABLATE_METHODS = (Method.AXIS_COMPOSED, Method.DIRECT26, Method.AUTO_SPMD,
                  Method.REMOTE_DMA)


def sweep_radii(face: int = 2, edge: int = 1):
    """The five shapes of the reference sweep (bin/bench_exchange.cu:126-195)."""
    px = Radius.constant(0)
    px.set_dir((1, 0, 0), face)

    x_only = Radius.constant(0)
    x_only.set_dir((1, 0, 0), face)
    x_only.set_dir((-1, 0, 0), face)

    faces = Radius.constant(0)
    faces.set_face(face)

    face_edge = Radius.constant(face)
    face_edge.set_corner(edge)

    uniform = Radius.constant(2)
    return [
        (f"px/{face}", px),
        (f"x/{face}", x_only),
        (f"faces/{face}", faces),
        (f"face&edge/{face}/{edge}", face_edge),
        ("uniform/2", uniform),
    ]


def run(x, y, z, iters=30, quantities=4, devices=None, method=Method.AXIS_COMPOSED,
        chunk=10, wire_dtype=None):
    devices = list(devices) if devices is not None else jax.devices()
    rows = []
    for name, radius in sweep_radii():
        r = time_exchange(
            Dim3(x, y, z), radius, iters, method=method, devices=devices,
            quantities=quantities, chunk=chunk, wire_dtype=wire_dtype,
        )
        rows.append(
            {
                "config": f"{x}-{y}-{z}/{name}",
                "bytes": r["bytes_logical"],
                "trimean_s": r["trimean_s"],
                "bytes_per_s": r["bytes_logical"] / r["trimean_s"],
            }
        )
    return rows


def compare_methods(x, y, z, iters=30, quantities=4, devices=None, radius=2,
                    methods=ABLATE_METHODS):
    """The three exchange strategies at a uniform radius — the pack-strategy
    ablation (see module docstring)."""
    devices = list(devices) if devices is not None else jax.devices()
    rows = []
    for method in methods:
        try:
            r = time_exchange(
                Dim3(x, y, z), Radius.constant(radius), iters, method=method,
                devices=devices, quantities=quantities,
            )
        except ValueError as e:
            # a method constraint (e.g. block size < radius after the
            # NodePartition's split) should report the skip instead of
            # crashing after the main sweep
            print(f"# skipping {method.value}: {e}")
            continue
        rows.append(
            {
                "config": f"{x}-{y}-{z}/method={method.value}",
                "bytes": r["bytes_logical"],
                "trimean_s": r["trimean_s"],
                "bytes_per_s": r["bytes_logical"] / r["trimean_s"],
                "domain": r["domain"],
                "census": r["census"],
            }
        )
    return rows


def ablate(x, y, z, iters=30, quantities=4, devices=None, radius=2):
    """Run all three methods back-to-back at a uniform radius: wall-clock,
    collective census (counts + interconnect bytes from the compiled HLO),
    and a bit-for-bit agreement check of one exchange on coordinate fields.

    Returns ``(rows, agree)``; each row carries ``cp_count``/``cp_bytes``
    (collective-permutes) and ``other_collectives`` (any all-gather/
    all-reduce/... the partitioner snuck in — 0 for a pure permute plan).
    Bitwise agreement across ALL methods is only guaranteed at a uniform
    radius: under anisotropic gating DIRECT26 skips inactive directions
    that the composed full-extent slabs incidentally fill."""
    rows = compare_methods(
        x, y, z, iters=iters, quantities=quantities, devices=devices,
        radius=radius,
    )
    rec = telemetry.get()
    outs = {}
    for row in rows:
        dd = row.pop("domain")
        ex = dd.halo_exchange
        state = coord_state(dd, quantities)
        # the census is a STATIC truth (shapes + method, not values), so a
        # metrics-enabled run reuses the one time_exchange already compiled
        # and recorded; otherwise lower/compile it here — the same state
        # then feeds (and is donated to) the agreement exchange
        census = row.pop("census", None)
        if census is None:
            census = ex.collective_census(state)
            if rec.enabled:
                telemetry.record_census(census, rec, method=ex.method.value)
        cp = census.get("collective-permute", (0, 0))
        row["cp_count"] = cp[0]
        row["cp_bytes"] = cp[1]
        row["other_collectives"] = sum(
            c for k, (c, _b) in census.items() if k != "collective-permute"
        )
        out = ex(state)
        outs[row["config"]] = np.stack(
            [np.asarray(jax.device_get(out[i])) for i in sorted(out)]
        )
    vals = list(outs.values())
    agree = all(np.array_equal(vals[0], v) for v in vals[1:])
    if rec.enabled:
        rec.gauge("ablate.bit_for_bit_agreement", int(agree), phase="verify")
    return rows, agree


def batched_ab(x, y, z, iters=30, quantities=(1, 4, 8), devices=None,
               radius=2, partition=None):
    """Quantity-batching A/B: at each Q, time the batched exchange (one
    packed ``(Q, ...)`` carrier per collective — Q-independent permute
    count) against the historical per-quantity program on the SAME domain
    shape, with the collective census of both compiled programs and a
    field-for-field bit-parity check of one exchange on coordinate fields.

    Returns ``(rows, q_independent, parity)``: ``q_independent`` is True
    iff the batched permute count is identical across every Q (the
    tentpole claim — e.g. 6 at Q=1 and Q=8 on a 2×2×2 mesh, where the
    per-quantity column reads 6·Q); ``parity`` is True iff batched and
    per-quantity results agree bitwise at every Q."""
    devices = list(devices) if devices is not None else jax.devices()
    rec = telemetry.get()
    rows = []
    batched_counts = {}
    parity = True
    for q in quantities:
        outs = {}
        for batched in (True, False):
            r = time_exchange(
                Dim3(x, y, z), Radius.constant(radius), iters,
                devices=devices, quantities=q, batch_quantities=batched,
                partition=partition,
            )
            dd = r["domain"]
            ex = dd.halo_exchange
            state = coord_state(dd, q)
            census = r.pop("census", None)
            if census is None:
                # metrics disabled (census is non-None exactly when the
                # recorder is on — time_exchange already recorded it,
                # batched-tagged, in that case): compile it for the table
                census = ex.collective_census(state)
            cp = census.get("collective-permute", (0, 0))
            label = "batched" if batched else "per-quantity"
            rows.append({
                "config": f"{x}-{y}-{z}/q={q}/{label}",
                "bytes": r["bytes_logical"],
                "trimean_s": r["trimean_s"],
                "bytes_per_s": r["bytes_logical"] / r["trimean_s"],
                "cp_count": cp[0],
                "cp_bytes": cp[1],
                "other_collectives": sum(
                    c for k, (c, _b) in census.items()
                    if k != "collective-permute"
                ),
            })
            if batched:
                batched_counts[q] = cp[0]
            # one exchange on coordinate fields for the parity gate (the
            # state is donated to it, so gather the result immediately)
            out = ex(state)
            outs[batched] = np.stack(
                [np.asarray(jax.device_get(out[i])) for i in sorted(out)]
            )
        if not np.array_equal(outs[True], outs[False]):
            parity = False
    q_independent = len(set(batched_counts.values())) == 1
    if rec.enabled:
        rec.gauge("batched_ab.q_independent", int(q_independent),
                  phase="verify")
        rec.gauge("batched_ab.bit_for_bit_agreement", int(parity),
                  phase="verify")
    return rows, q_independent, parity


def wire_gate(wire: str):
    """(byte-ratio threshold, relative error bound) the wire A/B gates
    one compression dtype on, derived from the dtype itself so every
    tier shares one rule: the on-wire byte reduction must reach 95% of
    the ideal fp32-native ratio (bf16 → 1.9x, the fp8 tier → 3.8x), and
    the measured max relative error must sit within the wire dtype's
    rounding half-ulp, 2^-(mantissa bits incl. implicit) (bf16 → 2^-8,
    float8_e4m3fn → 2^-4). ``jnp.finfo`` resolves the ml_dtypes types
    (bfloat16/float8_*) that numpy's finfo rejects."""
    wdt = jnp.dtype(wire)
    ratio_thr = 0.95 * (4.0 / wdt.itemsize)
    rel_bound = 2.0 ** -(jnp.finfo(wdt).nmant + 1)
    return ratio_thr, rel_bound


def wire_ab(x, y, z, iters=30, quantities=4, devices=None, radius=2,
            wire="bfloat16", method=Method.AXIS_COMPOSED, partition=None,
            fused: bool = False):
    """Wire-compression A/B (bf16 or the fp8 tier): the same exchange
    with native carriers vs ``wire``-compressed ones, reporting the
    on-wire byte reduction and the measured error the compression pays
    for it. ``fused`` A/Bs the fused compute+exchange transport's
    concurrent per-direction carriers instead (REMOTE_DMA only).

    Narrow-range wire dtypes (float8_e4m3fn tops out at 448 and maps
    overflow to NaN) get the coordinate fixture scaled into their finite
    range first — the same policy user data must follow: fp8 wire is
    for fields whose halos live inside the format's range.

    Bytes come from :func:`~stencil_tpu.utils.hlo_check.stablehlo_wire_census`
    over each leg's LOWERED program — the pre-backend-optimization truth.
    (The compiled-HLO census is still recorded when metrics are on, but
    the CPU backend's float-normalization pass widens bf16 collectives
    back to f32, so only a TPU's compiled census can confirm the ratio
    in silicon; the lowered module is what the exchange asks the wire to
    carry, and is exact for the hand-written permute methods.)

    Error gauges (vs the full-precision leg, on coordinate fields):
    ``wire_ab.max_abs_err``, ``wire_ab.max_rel_err`` and
    ``wire_ab.max_ulp_err`` (float32 ULPs between the two results).
    Returns ``(rows, bytes_ratio, err)``."""
    from ..utils.hlo_check import stablehlo_wire_census

    if method == Method.AUTO_SPMD:
        raise ValueError(
            "--wire-ab has no meaning for auto-spmd: the partitioner owns "
            "the schedule and packs no carriers to compress"
        )
    devices = list(devices) if devices is not None else jax.devices()
    rec = telemetry.get()
    rows = []
    outs = {}
    wire_bytes = {}
    # narrow-range wire dtypes: scale the coordinate fixture so no halo
    # value exceeds the format's finite range (overflow is NaN there)
    peak = (z - 1) * 1e6 + (y - 1) * 1e3 + (x - 1) + quantities
    fin_max = float(jnp.finfo(jnp.dtype(wire)).max)
    scale = min(1.0, fin_max / (2.0 * peak))
    for wd in (None, wire):
        r = time_exchange(
            Dim3(x, y, z), Radius.constant(radius), iters, method=method,
            devices=devices, quantities=quantities, wire_dtype=wd,
            partition=partition, fused=fused,
        )
        dd = r["domain"]
        ex = dd.halo_exchange
        state = coord_state(dd, quantities)
        if scale < 1.0:
            state = {k: v * jnp.asarray(scale, v.dtype)
                     for k, v in state.items()}
        # the lowered-module wire truth (see docstring); REMOTE_DMA has
        # no single lowered program — its wire bytes come from the plan
        if method == Method.REMOTE_DMA:
            itemsizes = [np.dtype("float32").itemsize] * quantities
            wire_bytes[wd] = ex.plan.wire_bytes(itemsizes)
            cp = (0, wire_bytes[wd])
        else:
            census = stablehlo_wire_census(
                ex._compiled.lower(state).as_text())
            cp = census.get("collective-permute", (0, 0))
            wire_bytes[wd] = cp[1]
        label = f"wire={wd or 'native'}"
        rows.append({
            "config": f"{x}-{y}-{z}/q={quantities}/{label}",
            "bytes": r["bytes_logical"],
            "trimean_s": r["trimean_s"],
            "bytes_per_s": r["bytes_logical"] / r["trimean_s"],
            "cp_count": cp[0],
            "cp_bytes": cp[1],
            "other_collectives": 0,
        })
        out = ex(state)
        outs[wd] = np.stack(
            [np.asarray(jax.device_get(out[i])) for i in sorted(out)]
        )
    ratio = (wire_bytes[None] / wire_bytes[wire]
             if wire_bytes[wire] else 0.0)
    a, b = outs[None].astype(np.float32), outs[wire].astype(np.float32)
    abs_err = float(np.max(np.abs(a - b)))
    rel_err = float(np.max(np.abs(a - b) / np.maximum(np.abs(a), 1.0)))
    # ULP distance in float32: adjacent-representable steps between the
    # two results (monotone int reinterpretation; same-sign values here)
    ai = a.view(np.int32).astype(np.int64)
    bi = b.view(np.int32).astype(np.int64)
    ulp_err = float(np.max(np.abs(ai - bi)))
    err = {"max_abs_err": abs_err, "max_rel_err": rel_err,
           "max_ulp_err": ulp_err}
    if rec.enabled:
        rec.gauge("wire_ab.bytes_ratio", ratio, phase="verify", wire=wire)
        rec.gauge("wire_ab.max_abs_err", abs_err, phase="verify", wire=wire)
        rec.gauge("wire_ab.max_rel_err", rel_err, phase="verify", wire=wire)
        rec.gauge("wire_ab.max_ulp_err", ulp_err, phase="verify", wire=wire)
    return rows, ratio, err


def report_header() -> str:
    return "config,bytes,trimean (s),B/s"


def report_row(row: dict) -> str:
    return f"{row['config']},{row['bytes']},{row['trimean_s']:e},{row['bytes_per_s']:e}"


def ablate_header() -> str:
    return "config,bytes,trimean (s),B/s,collective-permutes,cp bytes,other collectives"


def ablate_row(row: dict) -> str:
    return (
        f"{report_row(row)},{row['cp_count']},{row['cp_bytes']},"
        f"{row['other_collectives']}"
    )


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="halo exchange radius-shape sweep")
    p.add_argument("--x", type=int, default=256)
    p.add_argument("--y", type=int, default=256)
    p.add_argument("--z", type=int, default=256)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--method", choices=[m.value for m in Method],
                   default=Method.AXIS_COMPOSED.value,
                   help="exchange strategy for the radius sweep")
    p.add_argument("--methods", action="store_true",
                   help="also compare the three strategies (pack ablation)")
    p.add_argument("--ablate", action="store_true",
                   help="run ONLY the three-method ablation, with collective "
                        "census columns and a bit-for-bit agreement gate "
                        "(exit 1 on disagreement)")
    p.add_argument("--quantities", default="",
                   help="quantity count for the sweeps (single int; default "
                        "4), or a comma list of Qs for --batched-ab "
                        "(default 1,4,8)")
    p.add_argument("--batched-ab", action="store_true",
                   help="run ONLY the quantity-batching A/B: batched vs "
                        "per-quantity collectives at each Q with census "
                        "columns; exit 1 unless the batched permute count "
                        "is Q-independent and results agree bit-for-bit")
    p.add_argument("--partition", default="",
                   help="force the partition grid as XxYxZ (e.g. 2x2x2) "
                        "for --batched-ab / --wire-ab")
    p.add_argument("--wire-ab", action="store_true",
                   help="run ONLY the bf16-on-the-wire A/B: native vs "
                        "--wire-dtype compressed carriers, with on-wire "
                        "byte columns (lowered-module census) and the "
                        "measured max abs/rel/ulp error vs full precision; "
                        "exit 1 unless the byte reduction is >= 1.9x and "
                        "the error sits within the wire dtype's rounding "
                        "bound")
    p.add_argument("--wire-dtype", default="",
                   help="wire-compression dtype (bfloat16 or the fp8 "
                        "tier float8_e4m3fn): the radius sweep runs "
                        "with it on; --wire-ab A/Bs it against native "
                        "(default bfloat16 there)")
    p.add_argument("--fused", action="store_true",
                   help="use the fused compute+exchange transport "
                        "(REMOTE_DMA kernel_variant=fused) for --wire-ab")
    p.add_argument("--cpu", type=int, default=0)
    p.add_argument("--virtual-hosts", type=int, default=0,
                   help="emulate N hosts over the local device list "
                        "(sets STENCIL_VIRTUAL_HOSTS: id-sorted "
                        "contiguous groups) — the in-process fabric the "
                        "hierarchical ICI+DCN exchange benches on")
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    if args.virtual_hosts:
        import os

        os.environ["STENCIL_VIRTUAL_HOSTS"] = str(args.virtual_hosts)
    start_metrics(args, "bench_exchange")
    qs = [int(t) for t in str(args.quantities).split(",") if t.strip()]
    if args.wire_ab:
        partition = None
        if args.partition:
            partition = tuple(int(t) for t in args.partition.split("x"))
        if len(qs) > 1:
            p.error("--wire-ab takes a single --quantities value")
        wire = args.wire_dtype or "bfloat16"
        rows, ratio, err = wire_ab(
            args.x, args.y, args.z, iters=args.iters,
            quantities=qs[0] if qs else 4, wire=wire,
            method=Method(args.method), partition=partition,
            fused=args.fused,
        )
        print(ablate_header())
        for row in rows:
            print(ablate_row(row))
        print(f"# on-wire byte reduction ({wire}): {ratio:.3f}x")
        print(f"# max abs err {err['max_abs_err']:.6g}  max rel err "
              f"{err['max_rel_err']:.3e}  max f32-ulp err "
              f"{err['max_ulp_err']:.0f}")
        # dtype-derived gate (wire_gate): >= 95% of the ideal fp32-native
        # byte ratio (bf16 1.9x, fp8 3.8x), error within the wire dtype's
        # rounding half-ulp, and an UNCHANGED permute/DMA count — the
        # compression must never change what moves, only how wide
        ratio_thr, rel_bound = wire_gate(wire)
        count_ok = len({row["cp_count"] for row in rows}) == 1
        ok = (ratio >= ratio_thr and err["max_rel_err"] <= rel_bound
              and count_ok)
        print(f"# wire A/B gate (>={ratio_thr:g}x bytes, rel err <= "
              f"{rel_bound:g}, count unchanged): "
              f"{'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1
    if args.batched_ab:
        partition = None
        if args.partition:
            partition = tuple(int(t) for t in args.partition.split("x"))
        rows, q_indep, parity = batched_ab(
            args.x, args.y, args.z, iters=args.iters,
            quantities=tuple(qs) if qs else (1, 4, 8), partition=partition,
        )
        print(ablate_header())
        for row in rows:
            print(ablate_row(row))
        print(f"# batched permute count Q-independent: "
              f"{'PASS' if q_indep else 'FAIL'}")
        print(f"# batched vs per-quantity bit-for-bit: "
              f"{'PASS' if parity else 'FAIL'}")
        return 0 if q_indep and parity else 1
    if len(qs) > 1:
        # a silent truncation to qs[0] would print plausible rows for a
        # configuration the user did not ask for
        p.error("a comma list of --quantities requires --batched-ab")
    nq = qs[0] if qs else 4
    if args.ablate:
        rows, agree = ablate(args.x, args.y, args.z, iters=args.iters,
                             quantities=nq)
        print(ablate_header())
        for row in rows:
            print(ablate_row(row))
        print(f"# bit-for-bit agreement: {'PASS' if agree else 'FAIL'}")
        return 0 if agree and len(rows) == len(ABLATE_METHODS) else 1
    print(report_header())
    for row in run(args.x, args.y, args.z, iters=args.iters,
                   method=Method(args.method), quantities=nq,
                   wire_dtype=args.wire_dtype or None):
        print(report_row(row))
    if args.methods:
        for row in compare_methods(args.x, args.y, args.z, iters=args.iters,
                                   quantities=nq):
            row.pop("domain", None)
            print(report_row(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
