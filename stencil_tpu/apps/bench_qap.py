"""bench-qap — QAP solver benchmark.

TPU-native port of the reference solver benchmark (reference:
bin/bench_qap.cu:16-60): times the exact and greedy solvers on random,
matched (cost rewards identity), and block-diagonal matrices across sizes,
comparing the native C++ and pure-Python implementations.

``--derived`` additionally times both solvers on the REAL placement
inputs of the topology-aware plan leg — the wire-volume matrix of a
GridSpec (``plan/cost.placement_wire_matrix``, the same halo_extent
geometry the IR's wire model prices) against the link-cost matrix read
from the live devices (``parallel/topology.link_cost_matrix``: ICI hop
distance on TPU, the process-boundary ladder elsewhere) — and records
``qap.placement_cost`` (the best achieved wire-bytes x link-cost) and
``qap.improvement`` (identity cost / best cost; 1.0 where identity is
already optimal, e.g. any uniform single-process CPU mesh) gauges.

Usage: python -m stencil_tpu.apps.bench_qap --sizes 4 6 8 --catch-sizes 16 32 64
       python -m stencil_tpu.apps.bench_qap --derived --cpu 8 --x 64 \
           --partition 1x2x4
"""

from __future__ import annotations

import argparse
import time
from typing import Optional

import numpy as np

from ..parallel import qap


def make_matrices(kind: str, n: int, rng: np.random.RandomState):
    if kind == "random":
        w = rng.rand(n, n)
        d = rng.rand(n, n)
    elif kind == "matched":
        # distance ~ weight so identity is near-optimal
        w = rng.rand(n, n)
        d = 1.0 / (w + 0.1)
    elif kind == "block":
        blocks = -(-n // 4)  # ceil: kron result must cover n before cropping
        w = np.kron(np.eye(blocks), np.ones((4, 4)))[:n, :n] + 0.01
        d = rng.rand(n, n)
    else:
        raise ValueError(kind)
    np.fill_diagonal(w, 0)
    np.fill_diagonal(d, 0)
    return w, d


def run(sizes=(4, 6, 8), catch_sizes=(16, 32, 64), timeout_s=2.0):
    rng = np.random.RandomState(0)
    rows = []
    for kind in ("random", "matched", "block"):
        for n in sizes:
            w, d = make_matrices(kind, n, rng)
            for use_native in (True, False):
                if not use_native and n > 6:
                    continue  # pure-Python exhaustive search is too slow
                t0 = time.perf_counter()
                _, cost = qap.solve(w, d, timeout_s=timeout_s, use_native=use_native)
                rows.append(
                    {
                        "solver": "exact" + ("-native" if use_native else "-py"),
                        "kind": kind,
                        "n": n,
                        "cost": cost,
                        "s": time.perf_counter() - t0,
                    }
                )
        for n in catch_sizes:
            w, d = make_matrices(kind, n, rng)
            for use_native in (True, False):
                t0 = time.perf_counter()
                _, cost = qap.solve_catch(w, d, use_native=use_native)
                rows.append(
                    {
                        "solver": "catch" + ("-native" if use_native else "-py"),
                        "kind": kind,
                        "n": n,
                        "cost": cost,
                        "s": time.perf_counter() - t0,
                    }
                )
    return rows


def run_derived(x: int, y: int, z: int, radius: int, partition,
                ndev: int, timeout_s: float, itemsize: int = 4) -> list:
    """Time ``solve`` vs ``solve_catch`` on the DERIVED placement
    matrices — the plan leg's real inputs, not synthetic fixtures. The
    link-cost matrix comes from the live backend's devices, so this is
    the one bench row that measures what an autotune-time placement
    search actually pays. Imports jax lazily: the synthetic rows stay
    backend-free."""
    import jax

    from ..domain.grid import GridSpec
    from ..geometry import Dim3, Radius
    from ..parallel.topology import link_cost_matrix
    from ..plan.cost import placement_cost, placement_wire_matrix

    devices = jax.devices()[:ndev] if ndev else jax.devices()
    part = Dim3.of(partition)
    if part.flatten() != len(devices):
        raise SystemExit(
            f"--partition {part} needs {part.flatten()} devices; "
            f"{len(devices)} available")
    spec = GridSpec(Dim3(x, y, z), part, Radius.constant(radius))
    w = placement_wire_matrix(spec, part, per_cell_bytes=itemsize)
    link = link_cost_matrix(devices)
    identity = placement_cost(w, link)
    rows = []
    for solver, fn in (("exact", lambda: qap.solve(w, link,
                                                   timeout_s=timeout_s)),
                       ("catch", lambda: qap.solve_catch(w, link))):
        t0 = time.perf_counter()
        f, cost = fn()
        rows.append({
            "solver": solver, "kind": "derived", "n": len(devices),
            "cost": cost, "s": time.perf_counter() - t0,
            "identity_cost": identity,
            "improvement": (identity / cost) if cost > 0 else 1.0,
            "assignment": f,
        })
    return rows


def main(argv: Optional[list] = None) -> int:
    from ..obs import telemetry
    from ._bench_common import add_metrics_flags, finish_metrics, start_metrics

    p = argparse.ArgumentParser(description="QAP solver benchmark")
    p.add_argument("--sizes", type=int, nargs="+", default=[4, 6, 8])
    p.add_argument("--catch-sizes", type=int, nargs="+", default=[16, 32, 64])
    p.add_argument("--timeout", type=float, default=2.0)
    p.add_argument("--derived", action="store_true",
                   help="also time both solvers on the derived placement "
                        "matrices (GridSpec wire volumes x live-device "
                        "link costs) and record qap.placement_cost / "
                        "qap.improvement")
    p.add_argument("--x", type=int, default=64)
    p.add_argument("--y", type=int, default=64)
    p.add_argument("--z", type=int, default=64)
    p.add_argument("--radius", type=int, default=2,
                   help="halo radius of the --derived GridSpec")
    p.add_argument("--partition", default="",
                   help="--derived block grid as PXxPYxPZ (default: "
                        "2x2x2 at 8 devices, 1x1xN otherwise)")
    p.add_argument("--ndev", type=int, default=0,
                   help="--derived device count (0 = all)")
    p.add_argument("--cpu", type=int, default=0,
                   help="force N virtual CPU devices (--derived)")
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    rec = start_metrics(args, "bench_qap")
    print("solver,kind,n,cost,s")
    for row in run(tuple(args.sizes), tuple(args.catch_sizes), args.timeout):
        print(f"{row['solver']},{row['kind']},{row['n']},{row['cost']:.4f},{row['s']:.4f}")
        if rec.enabled:
            # per-row solver wall-clock + achieved cost, tagged like the
            # other bench apps so apps/report.py aggregates per solver
            # ('matrix' tag, not 'kind': that word is the record-kind
            # field of the telemetry schema itself)
            rec.gauge("qap.solve_s", row["s"], phase="solve", unit="s",
                      solver=row["solver"], matrix=row["kind"], n=row["n"])
            rec.gauge("qap.cost", row["cost"], phase="solve",
                      solver=row["solver"], matrix=row["kind"], n=row["n"])
    if args.derived:
        import jax

        ndev = args.ndev or len(jax.devices())
        part = args.partition or ("2x2x2" if ndev == 8 else f"1x1x{ndev}")
        part = tuple(int(v) for v in part.split("x"))
        for row in run_derived(args.x, args.y, args.z, args.radius, part,
                               ndev, args.timeout):
            print(f"{row['solver']}-derived,{row['kind']},{row['n']},"
                  f"{row['cost']:.4f},{row['s']:.4f},"
                  f"improvement={row['improvement']:.4f}")
            if rec.enabled:
                rec.gauge("qap.solve_s", row["s"], phase="solve", unit="s",
                          solver=row["solver"], matrix=row["kind"],
                          n=row["n"])
                rec.gauge("qap.placement_cost", row["cost"], phase="solve",
                          solver=row["solver"], matrix=row["kind"],
                          n=row["n"])
                rec.gauge("qap.improvement", row["improvement"],
                          phase="solve", solver=row["solver"],
                          matrix=row["kind"], n=row["n"])
    finish_metrics(rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
