"""bench-qap — QAP solver benchmark.

TPU-native port of the reference solver benchmark (reference:
bin/bench_qap.cu:16-60): times the exact and greedy solvers on random,
matched (cost rewards identity), and block-diagonal matrices across sizes,
comparing the native C++ and pure-Python implementations.

Usage: python -m stencil_tpu.apps.bench_qap --sizes 4 6 8 --catch-sizes 16 32 64
"""

from __future__ import annotations

import argparse
import time
from typing import Optional

import numpy as np

from ..parallel import qap


def make_matrices(kind: str, n: int, rng: np.random.RandomState):
    if kind == "random":
        w = rng.rand(n, n)
        d = rng.rand(n, n)
    elif kind == "matched":
        # distance ~ weight so identity is near-optimal
        w = rng.rand(n, n)
        d = 1.0 / (w + 0.1)
    elif kind == "block":
        blocks = -(-n // 4)  # ceil: kron result must cover n before cropping
        w = np.kron(np.eye(blocks), np.ones((4, 4)))[:n, :n] + 0.01
        d = rng.rand(n, n)
    else:
        raise ValueError(kind)
    np.fill_diagonal(w, 0)
    np.fill_diagonal(d, 0)
    return w, d


def run(sizes=(4, 6, 8), catch_sizes=(16, 32, 64), timeout_s=2.0):
    rng = np.random.RandomState(0)
    rows = []
    for kind in ("random", "matched", "block"):
        for n in sizes:
            w, d = make_matrices(kind, n, rng)
            for use_native in (True, False):
                if not use_native and n > 6:
                    continue  # pure-Python exhaustive search is too slow
                t0 = time.perf_counter()
                _, cost = qap.solve(w, d, timeout_s=timeout_s, use_native=use_native)
                rows.append(
                    {
                        "solver": "exact" + ("-native" if use_native else "-py"),
                        "kind": kind,
                        "n": n,
                        "cost": cost,
                        "s": time.perf_counter() - t0,
                    }
                )
        for n in catch_sizes:
            w, d = make_matrices(kind, n, rng)
            for use_native in (True, False):
                t0 = time.perf_counter()
                _, cost = qap.solve_catch(w, d, use_native=use_native)
                rows.append(
                    {
                        "solver": "catch" + ("-native" if use_native else "-py"),
                        "kind": kind,
                        "n": n,
                        "cost": cost,
                        "s": time.perf_counter() - t0,
                    }
                )
    return rows


def main(argv: Optional[list] = None) -> int:
    from ..obs import telemetry
    from ._bench_common import add_metrics_flags, finish_metrics, start_metrics

    p = argparse.ArgumentParser(description="QAP solver benchmark")
    p.add_argument("--sizes", type=int, nargs="+", default=[4, 6, 8])
    p.add_argument("--catch-sizes", type=int, nargs="+", default=[16, 32, 64])
    p.add_argument("--timeout", type=float, default=2.0)
    add_metrics_flags(p)
    args = p.parse_args(argv)
    rec = start_metrics(args, "bench_qap")
    print("solver,kind,n,cost,s")
    for row in run(tuple(args.sizes), tuple(args.catch_sizes), args.timeout):
        print(f"{row['solver']},{row['kind']},{row['n']},{row['cost']:.4f},{row['s']:.4f}")
        if rec.enabled:
            # per-row solver wall-clock + achieved cost, tagged like the
            # other bench apps so apps/report.py aggregates per solver
            # ('matrix' tag, not 'kind': that word is the record-kind
            # field of the telemetry schema itself)
            rec.gauge("qap.solve_s", row["s"], phase="solve", unit="s",
                      solver=row["solver"], matrix=row["kind"], n=row["n"])
            rec.gauge("qap.cost", row["cost"], phase="solve",
                      solver=row["solver"], matrix=row["kind"], n=row["n"])
    finish_metrics(rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
