"""Rollback-with-backoff recovery: the guarded step loop.

The policy layer of the ``fault/`` stack: health.py detects, inject.py
manufactures, this module recovers. :func:`run_guarded` drives an app's
fused-chunk step loop and, on a :class:`~.health.NumericalFault`,

1. records the fault (``recover.fault``),
2. restores the newest *valid* checkpoint through the app's restore hook
   (``DistributedDomain.restore_checkpoint`` → ``ckpt/restore.find_resume``'s
   layered validation — a truncated newest snapshot falls back to the
   previous good one),
3. health-checks the *restored* state too; a poisoned snapshot is
   quarantined (``ckpt/restore.quarantine_snapshot``) and the next
   candidate is tried — a rollback must never reinstall the disease,
4. backs off exponentially on repeated faults at the same step, and
5. after ``max_rollbacks`` at one step (or with no checkpoint to roll
   back to), degrades LOUDLY: writes a JSON evidence bundle, records
   ``recover.aborted``, and raises :class:`RecoveryExhausted` — the apps
   exit with :data:`FAULT_RC`, which the watchdog classifies as the
   ``fault`` outcome (rc-distinct from stall/crash/the ckpt kill hook).

Ordering contract per chunk: **step → inject → health check → checkpoint**.
The check runs before the save, so a poisoned state is never persisted —
the checkpoints stay a clean rollback target by construction.

With no guard, injector, or restore hook configured the engine degrades
to the apps' historical plain chunk loop: same step programs (the engine
never wraps or recompiles them — zero HLO change), same checkpoint
cadence, same telemetry.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import telemetry
from ..obs.watchdog import FAULT_RC  # noqa: F401  (re-exported contract)
from ..utils import logging as log
from .health import HealthGuard, NumericalFault
from .inject import FaultPlan

EVIDENCE_ENV = "STENCIL_FAULT_EVIDENCE"
EVIDENCE_NAME = "fault-evidence.json"


class RecoveryExhausted(RuntimeError):
    """Recovery gave up: no checkpoint to roll back to, or the same step
    faulted more than ``max_rollbacks`` times. Apps exit
    :data:`FAULT_RC` on this."""

    def __init__(self, fault: NumericalFault, rollbacks: int,
                 evidence_path: Optional[str], reason: str):
        self.fault = fault
        self.rollbacks = rollbacks
        self.evidence_path = evidence_path
        self.reason = reason
        super().__init__(
            f"recovery exhausted after {rollbacks} rollback(s): {reason} "
            f"(last fault: {fault}; evidence: {evidence_path or 'unwritten'})"
        )


@dataclass
class RecoveryPolicy:
    """Rollback budget + backoff shape."""

    max_rollbacks: int = 3      # per fault step
    backoff_s: float = 0.25     # first-retry sleep; doubles per repeat
    backoff_max_s: float = 30.0

    def backoff(self, attempt: int) -> float:
        return min(self.backoff_max_s, self.backoff_s * (2 ** (attempt - 1)))


def chunk_plan(start: int, iters: int, chunk: int,
               every: Sequence[int] = (), at: Sequence[int] = ()) -> List[int]:
    """Fused-chunk schedule from ``start`` to ``iters``: chunks of at most
    ``chunk`` steps, additionally broken at every multiple of each nonzero
    cadence in ``every`` (checkpoint / health boundaries) and at each
    absolute step in ``at`` (injection steps — a fault must land at its
    exact step regardless of chunking)."""
    bounds = sorted(b for b in set(at) if start < b < iters)
    plan: List[int] = []
    d = start
    while d < iters:
        k = min(chunk, iters - d)
        for e in every:
            if e and e > 0:
                k = min(k, e - d % e)
        for b in bounds:
            if b > d:
                k = min(k, b - d)
                break
        plan.append(k)
        d += k
    return plan


def _crossed(prev: int, step: int, every: int) -> bool:
    return every > 0 and step // every > prev // every


def write_evidence(payload: dict, evidence_dir: Optional[str]) -> Optional[str]:
    """Persist the abort evidence bundle (best-effort: evidence must never
    mask the abort itself). ``STENCIL_FAULT_EVIDENCE`` overrides the full
    path; the default is ``<evidence_dir>/fault-evidence.json``."""
    path = os.environ.get(EVIDENCE_ENV) or os.path.join(
        evidence_dir or ".", EVIDENCE_NAME)
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)
    except OSError as e:
        log.warn(f"fault: could not write the evidence bundle {path}: {e}")
        return None
    return path


def run_guarded(
    state: Dict[str, "object"],
    *,
    start: int,
    iters: int,
    plan_fn: Callable[[int], Sequence[int]],
    step_fn: Callable[[Dict, int], Dict],
    guard: Optional[HealthGuard] = None,
    injector: Optional[FaultPlan] = None,
    policy: Optional[RecoveryPolicy] = None,
    save_fn: Optional[Callable[[int, Dict], None]] = None,
    ckpt_every: int = 0,
    restore_fn: Optional[Callable[[], Optional[Tuple[int, Dict]]]] = None,
    quarantine_fn: Optional[Callable[[int], None]] = None,
    flush_fn: Optional[Callable[[], None]] = None,
    on_chunk: Optional[Callable[[Dict, int, float, int], Optional[Dict]]] = None,
    spec=None,
    ckpt_dir: Optional[str] = None,
    evidence_dir: Optional[str] = None,
    app: Optional[str] = None,
    sentinel=None,
    sentinel_key: str = "step.latency_s",
    status=None,
    replan=None,
) -> Tuple[Dict, int]:
    """Drive the step loop from ``start`` to ``iters``; returns the final
    ``(state, step)``.

    - ``plan_fn(step)`` rebuilds the fused-chunk schedule from any step
      (called again after every rollback).
    - ``step_fn(state, k)`` advances ``k`` steps and must block until the
      result is real (the engine times it).
    - ``save_fn(step, state)`` persists a checkpoint; called when a chunk
      crosses a ``ckpt_every`` boundary, strictly AFTER the health check.
    - ``restore_fn() -> (step, state) | None`` is the rollback source
      (``None`` = nothing valid left → abort).
    - ``flush_fn()`` drains an async checkpoint writer; called before any
      read-back of the checkpoint dir (rollback restore, disk-level
      injections) so "newest snapshot" never races the writer thread.
    - ``quarantine_fn(step)`` renames a restored-but-poisoned snapshot
      aside so the next restore attempt skips it.
    - ``on_chunk(state, k, per_iter_s, step)`` observes each timed chunk
      (statistics, telemetry, dumps); may return a replacement state.
    - ``sentinel`` (:class:`~stencil_tpu.obs.live.LiveSentinel`) observes
      each chunk's whole-cycle per-step latency under ``sentinel_key`` —
      step + injection + health check + checkpoint, deliberately WIDER
      than the per-chunk step span (an injected slowdown or a slow save
      must be visible to the in-run sentinel the way it is to the
      wall-clock ledger leg). Detection emits ``anomaly.detected`` /
      ``replan.requested`` mid-run.
    - ``status`` (:class:`~stencil_tpu.obs.status.StatusWriter`) gets an
      atomic snapshot rewrite per chunk: current step, rolling latency,
      health counts, anomaly state — the file ``report --status`` polls.
    - ``replan`` (:class:`~stencil_tpu.plan.replan.ReplanController`)
      closes the ROADMAP #6 loop: when the sentinel's ``on_replan`` hook
      latched a request, the engine finishes the current chunk and then
      performs the swap — retune, install the new compiled plan, emit
      ``replan.applied``/``replan.rejected`` — BETWEEN chunks, where a
      rebuild cannot tear a step; a rejected swap continues on the old
      plan. The controller may return a re-sharded state (the new
      plan's partition may differ), which replaces ``state`` for the
      remaining chunks.
    """
    rec = telemetry.get()
    policy = policy or RecoveryPolicy()
    done = int(start)
    if injector is not None:
        dead = [s for s in injector.steps() if s <= start]
        if dead:
            log.warn(f"fault: injection step(s) {dead} are <= the start "
                     f"step {start} and will never fire (resumed past "
                     "them?)")
    rollbacks: Dict[int, int] = {}
    fault_log: List[dict] = []
    health_checks = 0
    # a campaign calls run_guarded once per slot segment on ONE shared
    # status writer: the health section accumulates on top of whatever
    # the snapshot already shows, so counts never regress mid-campaign
    base_health = {"checks": 0, "faults": 0, "rollbacks": 0}
    if status is not None and isinstance(status.doc.get("health"), dict):
        prev_h = status.doc["health"]
        base_health = {k: int(prev_h.get(k, 0)) for k in base_health}

    def _status_update(step: int, per: Optional[float] = None) -> None:
        if status is None:
            return
        status.update(
            step=int(step), iters=int(iters), per_step_s=per,
            steps_per_s=(1.0 / per if per and per > 0 else None),
            health={
                "checks": base_health["checks"] + health_checks,
                "faults": base_health["faults"] + len(fault_log),
                "rollbacks": (base_health["rollbacks"]
                              + sum(rollbacks.values())),
            },
            anomalies=sentinel.summary() if sentinel is not None else None,
        )

    def _abort(fault: NumericalFault, reason: str) -> None:
        payload = {
            "kind": "stencil-fault-evidence",
            "app": app,
            "t": time.time(),
            "rc": FAULT_RC,
            "reason": reason,
            "policy": {"max_rollbacks": policy.max_rollbacks,
                       "backoff_s": policy.backoff_s},
            "faults": fault_log,
            "rollbacks": {str(k): v for k, v in rollbacks.items()},
            "injections": injector.describe() if injector else [],
            "ckpt_dir": ckpt_dir,
            "metrics": os.environ.get("STENCIL_METRICS_OUT")
            or os.environ.get("STENCIL_BENCH_METRICS_OUT"),
        }
        path = write_evidence(payload, evidence_dir or ckpt_dir)
        rec.meta("recover.aborted", reason=reason, step=int(fault.step),
                 rollbacks=sum(rollbacks.values()), evidence=path)
        log.error(f"fault: recovery exhausted at step {fault.step} "
                  f"({reason}); evidence: {path}; exiting rc={FAULT_RC}")
        raise RecoveryExhausted(fault, sum(rollbacks.values()), path, reason)

    while True:
        plan = plan_fn(done)
        try:
            for k in plan:
                prev = done
                t0 = time.perf_counter()
                state = step_fn(state, k)
                per = (time.perf_counter() - t0) / k
                done = prev + k
                rec.note_step(done)  # heartbeat payload: last step reached
                if injector is not None:
                    state = injector.fire_due(state, prev, done, spec=spec,
                                              ckpt_dir=ckpt_dir,
                                              ckpt_flush=flush_fn)
                save_due = (save_fn is not None and done < iters
                            and _crossed(prev, done, ckpt_every))
                if guard is not None and (guard.due(prev, done) or save_due
                                          or done >= iters):
                    # a due save forces a check even off the health cadence:
                    # a poisoned state must never become a rollback target
                    guard.check(state, step=done)
                    health_checks += 1
                if save_due:
                    save_fn(done, state)
                cycle = per
                if sentinel is not None:
                    # the whole chunk cycle per step (step + injection +
                    # health + save): what the run actually sustains —
                    # an injected slowdown lands HERE, not in `per`
                    cycle = (time.perf_counter() - t0) / k
                    sentinel.observe(sentinel_key, cycle, step=done,
                                     unit="s")
                if on_chunk is not None:
                    state = on_chunk(state, k, per, done) or state
                # status AFTER on_chunk: a section owner riding on_chunk
                # (the campaign driver stages lanes via status.set) gets
                # its sections into the SAME atomic write — one
                # fsync+rename per chunk, not two
                _status_update(done, cycle)
                if replan is not None and replan.pending:
                    # the chunk is finished and its status is durable:
                    # the one safe point to swap the compiled plan.
                    # Remaining chunk sizes stay valid (they are step
                    # counts, not programs); the next step_fn call runs
                    # the new plan's compiled loop.
                    swapped = replan.maybe_swap(state, done)
                    if swapped is not None:
                        state = swapped
            return state, done
        except NumericalFault as f:
            n = rollbacks.get(f.step, 0) + 1
            rollbacks[f.step] = n
            fault_log.append({
                "kind": f.kind, "quantity": f.quantity, "step": f.step,
                "value": f.value, "t": time.time(), "attempt": n,
            })
            rec.meta("recover.fault", fault_kind=f.kind, quantity=f.quantity,
                     step=int(f.step), attempt=n)
            log.warn(f"fault: {f} (occurrence {n} at this step)")
            if restore_fn is None:
                _abort(f, "no checkpointing configured: cannot roll back")
            if n > policy.max_rollbacks:
                _abort(f, f"max rollbacks ({policy.max_rollbacks}) exceeded "
                          f"at step {f.step}")
            backoff = policy.backoff(n)
            rec.gauge("recover.backoff_s", backoff, phase="recover",
                      step=int(f.step), unit="s")
            log.warn(f"fault: backing off {backoff:g}s before rollback "
                     f"{n}/{policy.max_rollbacks}")
            time.sleep(backoff)
            # restore; the async writer is drained first so every save
            # already handed off is visible on disk. A restored state that
            # itself fails the guard is a poisoned snapshot — quarantine
            # it and fall further back
            if flush_fn is not None:
                flush_fn()
            restored = None
            for _ in range(policy.max_rollbacks + 8):
                found = restore_fn()
                if found is None:
                    _abort(f, "no valid checkpoint to roll back to")
                rstep, rstate = found
                try:
                    if guard is not None:
                        guard.check(rstate, step=rstep)
                except NumericalFault as g:
                    if quarantine_fn is None:
                        _abort(g, f"restored snapshot (step {rstep}) is "
                                  "poisoned and quarantine is unavailable")
                    log.warn(f"fault: restored step {rstep} is poisoned "
                             f"({g.kind} in {g.quantity!r}); quarantining")
                    quarantine_fn(rstep)
                    continue
                restored = (rstep, rstate)
                break
            if restored is None:
                _abort(f, "every restore candidate was poisoned")
            rstep, state = restored
            rec.counter("recover.rollback", value=1, phase="recover",
                        from_step=int(done), to_step=int(rstep),
                        fault_step=int(f.step))
            log.warn(f"fault: rolled back from step {done} to checkpointed "
                     f"step {rstep}")
            done = rstep
            rec.note_step(done)
            _status_update(done)  # the snapshot shows the rollback, live
