"""In-loop numerical health guard: one fused isfinite/max reduction.

The reference's failure model is "MPI aborts the job" — it has no defense
against *in-band* faults: a NaN burst from a bad device, a corrupted halo
payload, or a diverging field walks straight through the step loop and
either poisons the final state or (worse) silently poisons the
checkpoints, so ``--resume`` restores garbage. This module is the
detection layer of the ``stencil_tpu/fault`` self-healing stack
(inject.py manufactures the faults; recover.py rolls them back).

Design constraints:

- **One fused dispatch.** The guard compiles a single jitted program that
  reduces every quantity to ``(all-finite, max|u|)`` pairs — one host
  round-trip per check, not one per quantity. The per-check wall cost is
  recorded as a ``health.check`` span so the overhead is measurable in
  the metrics JSONL, never guessed.
- **Zero HLO change when disabled.** The guard NEVER wraps or rewrites
  the step program — it is a *separate* compiled reduction run on the
  state between fused chunks. A run with the guard off executes the
  byte-identical step-loop HLO (pinned by tests/test_fault_health.py the
  way tests/test_overlap_hlo.py pins the overlap structure).
- **Typed faults.** A failed check raises :class:`NumericalFault` naming
  the offending quantity, the step, and the fault kind (``nonfinite`` |
  ``divergence``) — recover.py's rollback policy and the apps' exit
  codes dispatch on it.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import telemetry

#: NumericalFault kinds, in the order the checks run.
NONFINITE = "nonfinite"
DIVERGENCE = "divergence"


class NumericalFault(RuntimeError):
    """An in-band numerical fault: non-finite values or a blown ceiling.

    Carries the offending ``quantity`` name, the ``step`` the failed
    check observed, the fault ``kind``, and (when finite) the observed
    ``value`` (max |u| of the quantity).
    """

    def __init__(self, kind: str, quantity: str, step: int,
                 value: Optional[float] = None):
        self.kind = kind
        self.quantity = quantity
        self.step = int(step)
        self.value = value
        what = ("non-finite values" if kind == NONFINITE
                else f"max|u| = {value:g} over the divergence ceiling")
        super().__init__(
            f"numerical fault [{kind}] in quantity {quantity!r} at step "
            f"{step}: {what}"
        )


class HealthGuard:
    """Periodic fused health check over a ``{name: stacked array}`` state.

    ``every`` is the check cadence in steps (the loop engine calls
    :meth:`due` at chunk boundaries); ``max_abs`` adds the optional
    divergence ceiling on top of the isfinite sweep. One guard instance
    owns one jitted reduction — jit re-specializes per state
    shape/dtype structure, so a guard can serve several domains.
    """

    def __init__(self, every: int = 1, max_abs: Optional[float] = None):
        self.every = max(1, int(every))
        self.max_abs = float(max_abs) if max_abs else None
        self.checks = 0
        self._reduce = jax.jit(self._build)

    @staticmethod
    def _build(state):
        names = sorted(state)
        finite, amax = [], []
        for n in names:
            x = state[n]
            if jnp.issubdtype(x.dtype, jnp.inexact):
                finite.append(jnp.isfinite(x).all())
                # f32 is enough for the ceiling verdict: an fp64 magnitude
                # that overflows the cast reads as inf, which any ceiling
                # correctly calls divergence
                amax.append(jnp.max(jnp.abs(x)).astype(jnp.float32))
            else:  # integer quantities are trivially healthy
                finite.append(jnp.array(True))
                amax.append(jnp.array(0.0, jnp.float32))
        return jnp.stack(finite), jnp.stack(amax)

    def due(self, prev_step: int, step: int) -> bool:
        """True when a check boundary (a multiple of ``every``) lies in
        ``(prev_step, step]``."""
        return step // self.every > prev_step // self.every

    def check(self, state: Dict[str, "jax.Array"], step: int) -> None:
        """Run the fused reduction; raise :class:`NumericalFault` on the
        first unhealthy quantity (telemetry gets a ``health.fault`` meta
        record first — the failed check is evidence either way)."""
        if not state:
            return
        rec = telemetry.get()
        self.checks += 1
        with rec.span("health.check", phase="health", step=int(step),
                      quantities=len(state)):
            finite, amax = self._reduce(dict(state))
            finite = np.asarray(jax.device_get(finite))
            amax = np.asarray(jax.device_get(amax))
        names = sorted(state)
        for i, name in enumerate(names):
            kind = None
            if not bool(finite[i]):
                kind = NONFINITE
            elif self.max_abs is not None and float(amax[i]) > self.max_abs:
                kind = DIVERGENCE
            if kind is None:
                continue
            value = float(amax[i])
            rec.meta("health.fault", fault_kind=kind, quantity=name,
                     step=int(step),
                     value=value if math.isfinite(value) else None,
                     ceiling=self.max_abs)
            raise NumericalFault(
                kind, name, step,
                value=value if math.isfinite(value) else None)
