"""Deterministic, seeded fault injection: prove the recovery paths fire.

SCR-style checkpoint/restart systems pair their snapshots with an
injection harness, because a recovery path that never runs is a recovery
path that does not work. This registry manufactures the faults the
``fault/`` stack defends against, each one deterministic (seeded
placement, exact step) and *recorded* — every firing emits a
``fault.injected`` telemetry record, so the evidence files of a faulted
run state exactly what was done to it.

Activation: the apps' ``--inject SPEC`` flag, or the
``STENCIL_FAULT_INJECT`` env var (flag wins). Placement randomness is
seeded from ``STENCIL_FAULT_SEED`` (default 0).

Spec grammar — comma/semicolon-separated items of ``kind@step[:k=v...]``:

- ``nan@K`` / ``inf@K``  — burst a small cube of NaN/Inf into one block's
  interior when the run crosses step K (options: ``q=NAME`` target
  quantity, ``cells=C`` cube side, default 2).
- ``halo@K``             — NaN into the wire-visible interior boundary
  slab of one block: the next exchange carries the corruption into the
  neighbor's halo, modeling a corrupted halo payload.
- ``ckpt-truncate@K``    — truncate the newest snapshot's first payload
  file (the recovery must fall back to the previous good snapshot).
- ``stall@K``            — stop beating: sleep until the watchdog kills
  the run (STALL outcome).
- ``crash@K[:rc=N]``     — hard ``os._exit(rc)`` (default rc 7).
- ``slow@K[:seconds=S]`` — one-off sleep of S seconds (default 1.0),
  then continue (exercises slow-phase tolerance).

``repeat=N`` (or ``repeat=always``) re-fires an injection every time the
run crosses its step again — e.g. after a rollback — which is how the
max-rollbacks abort path is driven; the default is fire-once, so a
rolled-back run recomputes clean, bit-identical state.

``tenant=ID`` pins an injection to one tenant's lane in a multi-tenant
campaign (steps become tenant-relative there; see
stencil_tpu/campaign/inject.py). The single-domain plan ignores it.
"""

from __future__ import annotations

import os
import random
import re
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..obs import telemetry
from ..utils import logging as log

ENV_SPEC = "STENCIL_FAULT_INJECT"
ENV_SEED = "STENCIL_FAULT_SEED"

STATE_KINDS = ("nan", "inf", "halo")
KINDS = STATE_KINDS + ("ckpt-truncate", "stall", "crash", "slow")

_ITEM_RE = re.compile(r"^([a-z0-9-]+)@(\d+)((?::[a-z_]+=[^:]+)*)$")


@dataclass
class Injection:
    """One scheduled fault."""

    kind: str
    step: int
    quantity: Optional[str] = None
    cells: int = 2        # burst cube side length
    rc: int = 7           # crash exit code
    seconds: float = 1.0  # slow-phase sleep
    repeat: int = 1       # firings allowed; -1 = every crossing
    tenant: Optional[str] = None  # campaign lane targeting (campaign/inject)
    fired: int = 0

    def due(self, prev_step: int, step: int) -> bool:
        if not (prev_step < self.step <= step):
            return False
        return self.repeat < 0 or self.fired < self.repeat

    def describe(self) -> dict:
        d = {"kind": self.kind, "step": self.step, "fired": self.fired}
        if self.quantity:
            d["quantity"] = self.quantity
        if self.repeat != 1:
            d["repeat"] = self.repeat
        if self.tenant:
            d["tenant"] = self.tenant
        return d


def parse_spec(spec: str) -> List[Injection]:
    """Parse an injection spec string (raises ValueError with the
    offending item on any grammar error — a mistyped injection must
    never silently run the campaign un-faulted)."""
    out: List[Injection] = []
    for raw in re.split(r"[;,]", spec or ""):
        item = raw.strip()
        if not item:
            continue
        m = _ITEM_RE.match(item)
        if not m:
            raise ValueError(
                f"bad fault spec {item!r} (want kind@step[:key=val...])")
        kind, step, opts = m.group(1), int(m.group(2)), m.group(3)
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (have {KINDS})")
        if step < 1:
            # firing requires prev_step < step with prev_step >= 0, so a
            # step-0 injection can never fire — the campaign would run
            # un-faulted while claiming to be injected
            raise ValueError(
                f"fault step must be >= 1 in {item!r} (step 0 can never "
                "fire: injections land when the run crosses their step)")
        inj = Injection(kind=kind, step=step)
        for kv in filter(None, opts.split(":")):
            k, v = kv.split("=", 1)
            if k in ("q", "quantity"):
                inj.quantity = v
            elif k == "cells":
                inj.cells = int(v)
            elif k == "rc":
                inj.rc = int(v)
            elif k == "seconds":
                inj.seconds = float(v)
            elif k == "repeat":
                inj.repeat = -1 if v in ("always", "-1") else int(v)
            elif k == "tenant":
                # campaign lane targeting (stencil_tpu/campaign/inject.py):
                # pins the injection to one tenant's lane; the single-domain
                # FaultPlan ignores it (one domain IS the only tenant)
                inj.tenant = v
            else:
                raise ValueError(f"unknown fault option {k!r} in {item!r}")
        out.append(inj)
    return out


class FaultPlan:
    """The active injection schedule of one run.

    The loop engine (recover.run_guarded) calls :meth:`fire_due` at every
    chunk boundary with the step interval just executed; injections whose
    step lies inside fire exactly once (unless ``repeat``).
    """

    def __init__(self, injections: Sequence[Injection], seed: int = 0):
        self.injections = list(injections)
        self.seed = int(seed)

    @classmethod
    def from_spec(cls, spec: Optional[str] = None,
                  seed: Optional[int] = None) -> Optional["FaultPlan"]:
        """Build a plan from an explicit spec, falling back to the
        ``STENCIL_FAULT_INJECT`` env var; None when nothing is scheduled."""
        if spec is None:
            spec = os.environ.get(ENV_SPEC, "")
        injections = parse_spec(spec)
        if not injections:
            return None
        if seed is None:
            seed = int(os.environ.get(ENV_SEED, "0") or 0)
        return cls(injections, seed=seed)

    def steps(self) -> List[int]:
        """Every scheduled step — chunk plans break here so injections
        land at their exact step regardless of chunking."""
        return sorted({i.step for i in self.injections})

    def describe(self) -> List[dict]:
        return [i.describe() for i in self.injections]

    # -- firing ---------------------------------------------------------------
    def fire_due(self, state: Dict[str, "object"], prev_step: int,
                 step: int, spec=None, ckpt_dir: Optional[str] = None,
                 ckpt_flush=None):
        """Apply every injection scheduled in ``(prev_step, step]`` to
        ``state`` (a ``{name: stacked array}`` dict); returns the
        (possibly corrupted) state. Non-state kinds act on the process /
        the checkpoint dir instead. ``ckpt_flush`` drains an async
        checkpoint writer before disk-level injections, so "the newest
        snapshot" is deterministic, not a race with the writer thread."""
        for inj in self.injections:
            if not inj.due(prev_step, step):
                continue
            inj.fired += 1
            if inj.kind == "ckpt-truncate" and ckpt_flush is not None:
                ckpt_flush()
            state = self._apply(inj, state, spec, ckpt_dir)
        return state

    def _rng(self, inj: Injection) -> random.Random:
        # keyed on (seed, kind, step) ONLY — never the firing count: a
        # repeated injection (repeat=, or re-crossed after a rollback)
        # must corrupt the SAME cells every time, or "deterministic"
        # stops meaning anything (and a re-fire could land somewhere the
        # workload heals, e.g. jacobi's fixed-temperature sphere cells)
        return random.Random(repr((self.seed, inj.kind, inj.step)))

    def _record(self, inj: Injection, **extra) -> None:
        telemetry.get().meta(
            "fault.injected", fault_kind=inj.kind, step=int(inj.step),
            phase="fault", **extra)

    def _apply(self, inj: Injection, state, spec, ckpt_dir):
        if inj.kind in ("nan", "inf"):
            return self._corrupt_block(inj, state, spec)
        if inj.kind == "halo":
            return self._corrupt_halo(inj, state, spec)
        if inj.kind == "ckpt-truncate":
            target = None
            if ckpt_dir:
                target = truncate_newest_payload(ckpt_dir)
            self._record(inj, target=target)
            if target is None:
                log.warn(f"fault: ckpt-truncate@{inj.step} found no snapshot "
                         "to truncate")
            else:
                log.warn(f"fault: truncated checkpoint payload {target}")
            return state
        if inj.kind == "slow":
            self._record(inj, seconds=inj.seconds)
            log.warn(f"fault: slow@{inj.step} sleeping {inj.seconds:g}s")
            time.sleep(inj.seconds)
            return state
        if inj.kind == "stall":
            self._record(inj)
            log.warn(f"fault: stall@{inj.step} — sleeping until the "
                     "watchdog kills this run")
            # sleep in slices so an unsupervised test can interrupt
            for _ in range(3600):
                time.sleep(1.0)
            return state
        if inj.kind == "crash":
            self._record(inj, rc=inj.rc)
            log.warn(f"fault: crash@{inj.step} — os._exit({inj.rc})")
            os._exit(inj.rc)
        raise AssertionError(f"unhandled fault kind {inj.kind}")

    # -- state corruption -----------------------------------------------------
    def _pick_quantity(self, inj: Injection, state, rng) -> str:
        names = sorted(state)
        if inj.quantity is not None:
            if inj.quantity in state:
                return inj.quantity
            log.warn(f"fault: quantity {inj.quantity!r} not in state "
                     f"{names}; picking deterministically")
        return rng.choice(names)

    def _corrupt_block(self, inj: Injection, state, spec):
        """NaN/Inf burst: a ``cells``-sided cube inside one block's
        compute interior (seed-deterministic block + offset)."""
        rng = self._rng(inj)
        name = self._pick_quantity(inj, state, rng)
        val = float("nan") if inj.kind == "nan" else float("inf")
        arr = state[name]
        if spec is None:
            # spec-less (unit-test) path: corrupt the first cells of the
            # flattened array
            n = max(1, min(inj.cells, arr.size))
            flat = arr.reshape(-1).at[0:n].set(val)
            state = dict(state)
            state[name] = flat.reshape(arr.shape)
            self._record(inj, quantity=name, cells=n)
            return state
        d, off = spec.dim, spec.compute_offset()
        bi = (rng.randrange(d.x), rng.randrange(d.y), rng.randrange(d.z))
        sz = spec.block_size(bi)
        c = max(1, min(inj.cells, sz.x, sz.y, sz.z))
        x0 = off.x + rng.randrange(sz.x - c + 1)
        y0 = off.y + rng.randrange(sz.y - c + 1)
        z0 = off.z + rng.randrange(sz.z - c + 1)
        state = dict(state)
        state[name] = arr.at[
            bi[2], bi[1], bi[0], z0:z0 + c, y0:y0 + c, x0:x0 + c
        ].set(val)
        self._record(inj, quantity=name, cells=c ** 3,
                     block=list(bi), origin=[x0, y0, z0])
        log.warn(f"fault: {inj.kind}@{inj.step} burst {c}^3 cells into "
                 f"{name!r} block {bi}")
        return state

    def _corrupt_halo(self, inj: Injection, state, spec):
        """Corrupted-halo-payload model: NaN into the wire-visible
        interior boundary slab (the rows the next exchange sends), so the
        corruption propagates exactly like a bad halo payload would."""
        rng = self._rng(inj)
        name = self._pick_quantity(inj, state, rng)
        if spec is None:
            return self._corrupt_block(inj, state, spec)
        r = 0
        for dx, dy, dz in ((0, 0, 1), (0, 1, 0), (1, 0, 0)):
            r = spec.radius.dir(dx, dy, dz)
            if r > 0:
                axis = (dx, dy, dz)
                break
        if r <= 0:
            log.warn("fault: halo injection on a radius-0 domain degrades "
                     "to an interior burst")
            return self._corrupt_block(inj, state, spec)
        d, off = spec.dim, spec.compute_offset()
        bi = (rng.randrange(d.x), rng.randrange(d.y), rng.randrange(d.z))
        sz = spec.block_size(bi)
        c = max(1, min(inj.cells, sz.x, sz.y, sz.z))
        # the high-side boundary slab along the chosen axis
        zsl = slice(off.z, off.z + c)
        ysl = slice(off.y, off.y + c)
        xsl = slice(off.x, off.x + c)
        if axis == (0, 0, 1):
            zsl = slice(off.z + sz.z - r, off.z + sz.z)
        elif axis == (0, 1, 0):
            ysl = slice(off.y + sz.y - r, off.y + sz.y)
        else:
            xsl = slice(off.x + sz.x - r, off.x + sz.x)
        state = dict(state)
        state[name] = state[name].at[bi[2], bi[1], bi[0], zsl, ysl, xsl].set(
            float("nan"))
        self._record(inj, quantity=name, block=list(bi),
                     axis=list(axis), radius=r)
        log.warn(f"fault: halo@{inj.step} corrupted the boundary slab of "
                 f"{name!r} block {bi} along axis {axis}")
        return state


def truncate_newest_payload(ckpt_dir: str, nbytes: int = 16) -> Optional[str]:
    """Truncate the newest snapshot's first payload file (the
    ``ckpt-truncate`` injection body; also handy for tests). Returns the
    truncated path, or None when no snapshot exists."""
    from ..ckpt import list_snapshots, load_manifest

    snaps = list_snapshots(ckpt_dir)
    if not snaps:
        return None
    snap = os.path.join(ckpt_dir, snaps[-1])
    try:
        m = load_manifest(snap)
        path = os.path.join(snap, m["files"][0]["path"])
        with open(path, "r+b") as f:
            f.truncate(nbytes)
    except (OSError, ValueError, KeyError, IndexError) as e:
        log.warn(f"fault: could not truncate a payload under {snap}: {e}")
        return None
    return path
