"""Self-healing runs: detect, inject, recover.

The in-band fault-tolerance layer between PR 3's stall watchdog (outer,
process-level) and PR 4's crash-safe checkpoints (durable state):

- :mod:`health`  — the numerical health guard: one fused jitted
  isfinite/max reduction over the state every ``--health-every`` steps,
  raising a typed :class:`NumericalFault`; zero HLO change when off.
- :mod:`inject`  — deterministic, seeded fault injection (NaN/Inf burst,
  halo-payload corruption, checkpoint truncation, stall, crash, slow
  phase), every firing recorded as a ``fault.injected`` telemetry record.
- :mod:`recover` — the rollback-with-backoff policy driving a guarded
  step loop: restore the newest valid snapshot, quarantine poisoned
  ones, back off exponentially, and after ``--max-rollbacks`` abort with
  :data:`FAULT_RC` plus a JSON evidence bundle.

The executable acceptance proof is ``scripts/ci_fault_gate.py``.
"""

from .health import DIVERGENCE, NONFINITE, HealthGuard, NumericalFault  # noqa: F401
from .inject import (  # noqa: F401
    ENV_SEED,
    ENV_SPEC,
    FaultPlan,
    Injection,
    parse_spec,
    truncate_newest_payload,
)
from .recover import (  # noqa: F401
    EVIDENCE_ENV,
    EVIDENCE_NAME,
    FAULT_RC,
    RecoveryExhausted,
    RecoveryPolicy,
    chunk_plan,
    run_guarded,
    write_evidence,
)
