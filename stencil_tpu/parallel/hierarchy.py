"""Hierarchical (ICI + DCN) halo exchange — the two-level transport.

The reference is explicitly multi-node: its L2/L3 layers discover the
MPI/node topology and place blocks node-aware before any GPU-level
transport runs (reference: include/stencil/topology.hpp, NodeAware
placement via qap::solve). This module is that outer level for the TPU
port: an :class:`HierarchicalExchange` wraps a flat
:class:`~.exchange.HaloExchange` whose plan carries a ``hierarchy``
(axis, hosts) split, and moves the host-boundary slabs across the DCN
while the inner per-host program stays on the ICI.

Two schedules, chosen by the inner method:

- **overlapped** (AXIS_COMPOSED inner — the perf claim): extract the
  cross-host boundary slabs from the PRE-exchange state and START the
  DCN copies, run the compiled DCN-axis phase (host-local wrap pairs)
  while they fly — intra-host wire time hides the DCN latency, the same
  overlap shape the fused kernel uses for ICI DMAs — then apply the
  arrived slabs and run the remaining two axis phases, whose
  full-padded-extent slabs overwrite every stale strip. Because each
  phase's slabs span the full padded extents of the other axes, the
  composed exchange is order-insensitive, so running the DCN axis first
  is bit-identical to the flat x->y->z program.
- **sequential** (REMOTE_DMA family inner, fused/persistent variants
  included): run the FULL inner exchange first (its DCN-axis neighbor
  arithmetic wraps within each host segment — remote_emu._seg_wrap),
  then extract the sender boundary slabs POST-inner, when their
  orthogonal halos are already valid, and apply each to the receiver's
  whole DCN-axis halo side: one full-extent slab fixes face, edges and
  corners at once, overwriting every wrap-garbage cell (all of which
  are confined to that side by construction).

The DCN transport itself is the PR-10 host-orchestrated machinery
(parallel/remote_emu.py's take -> device_put -> update split): compiled
per-device take/update programs with ZERO collectives, carriers narrowed
to ``wire_dtype`` on extraction and widened on apply (one rounding —
exactly what the flat ppermute pays), and an executed-copy counter
(:attr:`last_transfer_count`) that analysis/verify_plan audits against
``plan.dcn_transfers_per_exchange``. In-process the "hosts" are the
``STENCIL_VIRTUAL_HOSTS`` fabric (parallel/device_topo.py); real
multi-process DCN is staged for the hardware session (ROADMAP #1).
"""

from __future__ import annotations

from functools import cached_property
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..ops.halo_fill import pack_slabs, unpack_slabs, wire_narrow_dtype
from ..utils import timer
from .device_topo import host_assignment, virtual_hosts
from .mesh import BLOCK_PSPEC


class HierarchicalExchange:
    """Two-level lowering of a hierarchical ExchangePlan.

    Built by :attr:`HaloExchange._compiled` when the plan's
    ``hierarchy`` names more than one host; callers use it exactly like
    the flat compiled exchange (``__call__``/``make_loop``/
    ``collective_census``)."""

    def __init__(self, ex):
        from .exchange import Method  # late: exchange.py builds us

        self.ex = ex
        self.mesh = ex.mesh
        self.plan = ex.plan
        if self.plan.hierarchy is None:
            raise ValueError("HierarchicalExchange needs a plan with a "
                             "hierarchy (got a flat plan)")
        self.axis, self.hosts = self.plan.hierarchy
        if self.hosts < 2:
            raise ValueError(
                f"hierarchy names {self.hosts} host(s) — the two-level "
                "transport needs >= 2 (a 1-host split is the flat plan)")
        self._composed = ex.method == Method.AXIS_COMPOSED
        if jax.process_count() > 1:
            raise NotImplementedError(
                "the hierarchical DCN transport is host-orchestrated "
                "in-process today (device_put between emulated hosts); "
                "real multi-process DCN rides the hardware session "
                "(ROADMAP #1)"
            )
        if not self._composed and ex._on_tpu():
            raise NotImplementedError(
                "hierarchical REMOTE_DMA on a TPU mesh is staged for the "
                "hardware session: the carrier kernels "
                "(ops/remote_dma.py, ops/fused_stencil.py) address the "
                "full ring, not host segments — use the AXIS_COMPOSED "
                "inner method or the CPU-emulation fabric "
                "(STENCIL_VIRTUAL_HOSTS)"
            )
        self._axis_of = {"z": 0, "y": 1, "x": 2}[self.axis]
        self._coords: Dict[int, Tuple[int, int, int]] = {}
        md = self.mesh.devices
        for iz in range(md.shape[0]):
            for iy in range(md.shape[1]):
                for ix in range(md.shape[2]):
                    self._coords[md[iz, iy, ix].id] = (iz, iy, ix)
        self.m = md.shape[self._axis_of]
        self.seg = self.m // self.hosts
        # the DCN axis geometry is the composed axis phase's — one
        # authority for offsets/sizes/radii (plan/ir.spec_axis)
        self._phase = next(
            p for p in self.plan.axis_phases if p.axis == self.axis
        )
        self._validate_alignment()
        self._jits: Dict[tuple, object] = {}
        self._avals: Dict[tuple, tuple] = {}
        self.last_transfer_count = 0  # executed DCN copies, last exchange
        self.last_transfer_bytes = 0  # executed DCN bytes, last exchange

    def _validate_alignment(self) -> None:
        """Every axis segment must live on exactly one distinct host:
        the outer split claims its boundary slabs cross the DCN and
        nothing else does, which is only true when the realized mesh
        groups each segment onto one host (the two-level placement
        composes device order to guarantee this; identity order aligns
        for a z split over contiguous hosts)."""
        devs = list(self.mesh.devices.flat)
        assign = host_assignment(devs)
        seg_host: Dict[int, int] = {}
        ok = True
        for d, h in zip(devs, assign):
            s = self._coords[d.id][self._axis_of] // self.seg
            if seg_host.setdefault(s, h) != h:
                ok = False
        if ok and len(set(seg_host.values())) != self.hosts:
            ok = False
        if not ok:
            hint = (
                f"set STENCIL_VIRTUAL_HOSTS={self.hosts} and realize "
                "with the two-level placement (plan/cost."
                "solve_two_level_placement) so device order groups each "
                "segment onto one host"
                if virtual_hosts() == 0
                else "realize with the two-level placement (plan/cost."
                "solve_two_level_placement) so device order groups each "
                "segment onto one host"
            )
            raise ValueError(
                f"hierarchical exchange: the {self.hosts} segments of "
                f"the {self.axis} axis do not align with the host "
                f"fabric (mesh-order host assignment {assign}); {hint}"
            )

    # -- compiled pieces ------------------------------------------------------
    def _jit(self, key, build):
        if key not in self._jits:
            self._jits[key] = jax.jit(build())
        return self._jits[key]

    def _remember(self, key, args) -> None:
        if key not in self._avals:
            self._avals[key] = tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args
            )

    def _device_sizes(self, i: int) -> Tuple[int, ...]:
        c = self._phase.resident
        return tuple(int(self._phase.sizes[i * c + j]) for j in range(c))

    def _take_fn(self, sizes, shard_shape, nq, wire, send_hi, send_lo):
        """take(*shards) -> the boundary carriers this segment-edge
        device sends across the DCN: +axis (``send_hi``) is its LAST
        resident's top ``rm`` rows, -axis (``send_lo``) its FIRST
        resident's bottom ``rp`` rows — full padded orthogonal extents
        (stale strips included; later/earlier inner phases overwrite
        them), packed per dtype group and narrowed to the wire dtype
        when compression is on (every DCN carrier crosses a wire)."""
        ph = self._phase
        rm, rp, off, adim, bdim, c = (ph.rm, ph.rp, ph.offset, ph.adim,
                                      ph.bdim, ph.resident)
        sz_last = sizes[c - 1]

        def slab(s, j, start, width):
            idx = [slice(None)] * len(shard_shape)
            idx[bdim] = slice(j, j + 1)
            idx[adim] = slice(start, start + width)
            return s[tuple(idx)]

        def take(*shards):
            out = []
            if send_hi:
                hi = pack_slabs([slab(s, c - 1, off + sz_last - rm, rm)
                                 for s in shards])
                out.append(hi.astype(wire) if wire is not None else hi)
            if send_lo:
                lo = pack_slabs([slab(s, 0, off, rp) for s in shards])
                out.append(lo.astype(wire) if wire is not None else lo)
            return tuple(out)

        return take

    def _update_fn(self, sizes, shard_shape, dtype, nq, wire,
                   has_lo, has_hi):
        """update(*shards, carriers...) -> new shards: write the
        received DCN carriers into this segment-edge device's halos —
        the low halo of its FIRST resident (``has_lo``, from the -axis
        host) and/or the high halo of its LAST resident (``has_hi``),
        widened back from the wire dtype."""
        ph = self._phase
        rm, rp, off, adim, bdim, c = (ph.rm, ph.rp, ph.offset, ph.adim,
                                      ph.bdim, ph.resident)

        def put(s, piece, j, start, width):
            idx = [slice(None)] * len(shard_shape)
            idx[bdim] = slice(j, j + 1)
            idx[adim] = slice(start, start + width)
            return s.at[tuple(idx)].set(piece)

        def update(*args):
            shards = list(args[:nq])
            rest = list(args[nq:])
            lo_q = hi_q = None
            if has_lo:
                lo = rest.pop(0)
                if wire is not None:
                    lo = lo.astype(dtype)
                lo_q = unpack_slabs(lo, nq)
            if has_hi:
                hi = rest.pop(0)
                if wire is not None:
                    hi = hi.astype(dtype)
                hi_q = unpack_slabs(hi, nq)
            out = []
            for q, s in enumerate(shards):
                o = s
                if has_lo:
                    o = put(o, lo_q[q], 0, off - rm, rm)
                if has_hi:
                    o = put(o, hi_q[q], c - 1, off + sizes[c - 1], rp)
                out.append(o)
            return tuple(out)

        return update

    # -- the DCN level --------------------------------------------------------
    def _groups(self, leaves) -> List[Tuple[object, List[int]]]:
        if not self.ex.batch_quantities:
            return [(leaves[i].dtype, [i]) for i in range(len(leaves))]
        groups: Dict[object, List[int]] = {}
        for i, leaf in enumerate(leaves):
            groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
        return list(groups.items())

    def _shards_by_coords(self, leaf):
        out = {}
        for sh in leaf.addressable_shards:
            out[self._coords[sh.device.id]] = sh.data
        return out

    def dcn_start(self, state):
        """Extract every cross-host boundary slab and START its copy
        toward the far side (``device_put``, issued but not synced — the
        caller's inner program dispatches while they fly). Returns the
        pending structure :meth:`dcn_apply` consumes."""
        leaves, _ = jax.tree.flatten(state)
        mdevs = self.mesh.devices
        ph = self._phase
        pending = {"sharding": self.ex.sharding(), "groups": []}
        for dtype, idxs in self._groups(leaves):
            nq = len(idxs)
            wire = wire_narrow_dtype(dtype, self.ex.wire_dtype)
            shards = [self._shards_by_coords(leaves[i]) for i in idxs]
            recv: Dict[Tuple[int, int, int], dict] = {}
            for coords in shards[0]:
                i = coords[self._axis_of]
                send_hi = ph.rm > 0 and i % self.seg == self.seg - 1
                send_lo = ph.rp > 0 and i % self.seg == 0
                if not (send_hi or send_lo):
                    continue
                sizes = self._device_sizes(i)
                args = tuple(s[coords] for s in shards)
                key = ("take", sizes, args[0].shape, str(dtype), nq,
                       str(wire), send_hi, send_lo)
                fn = self._jit(key, lambda: self._take_fn(
                    sizes, args[0].shape, nq, wire, send_hi, send_lo))
                self._remember(key, args)
                out = list(fn(*args))
                if send_hi:
                    # +axis: fills the low halo of the NEXT segment's
                    # first device (the flat ring pair the host-local
                    # wrap dropped)
                    dst = list(coords)
                    dst[self._axis_of] = (i + 1) % self.m
                    dst = tuple(dst)
                    car = jax.device_put(out.pop(0), mdevs[dst])
                    self.last_transfer_count += 1
                    self.last_transfer_bytes += int(car.nbytes)
                    recv.setdefault(dst, {})["lo"] = car
                if send_lo:
                    dst = list(coords)
                    dst[self._axis_of] = (i - 1) % self.m
                    dst = tuple(dst)
                    car = jax.device_put(out.pop(0), mdevs[dst])
                    self.last_transfer_count += 1
                    self.last_transfer_bytes += int(car.nbytes)
                    recv.setdefault(dst, {})["hi"] = car
            pending["groups"].append((dtype, idxs, recv))
        return pending

    def dcn_wait(self, pending) -> None:
        """Block until every started DCN copy has landed — the
        recv-semaphore wait of the overlap schedule."""
        for _dt, _idxs, recv in pending["groups"]:
            for per_dev in recv.values():
                for car in per_dev.values():
                    jax.block_until_ready(car)

    def dcn_apply(self, state, pending):
        """Wait, then write every arrived carrier into its receiver's
        DCN-axis halos (compiled updates, zero collectives) and
        reassemble the state."""
        self.dcn_wait(pending)
        leaves, treedef = jax.tree.flatten(state)
        order = [self._coords[d.id] for d in self.mesh.devices.flat]
        sharding = pending["sharding"]
        for dtype, idxs, recv in pending["groups"]:
            if not recv:
                continue
            nq = len(idxs)
            wire = wire_narrow_dtype(dtype, self.ex.wire_dtype)
            shards = [self._shards_by_coords(leaves[i]) for i in idxs]
            new_shards: Dict[Tuple[int, int, int], tuple] = {}
            for coords, per in recv.items():
                i = coords[self._axis_of]
                sizes = self._device_sizes(i)
                args = tuple(s[coords] for s in shards)
                has_lo, has_hi = "lo" in per, "hi" in per
                carriers = ([per["lo"]] if has_lo else []) \
                    + ([per["hi"]] if has_hi else [])
                key = ("upd", sizes, args[0].shape, str(dtype), nq,
                       str(wire), has_lo, has_hi)
                fn = self._jit(key, lambda: self._update_fn(
                    sizes, args[0].shape, dtype, nq, wire, has_lo,
                    has_hi))
                self._remember(key, tuple(args) + tuple(carriers))
                new_shards[coords] = fn(*args, *carriers)
            for q, li in enumerate(idxs):
                leaves[li] = jax.make_array_from_single_device_arrays(
                    leaves[li].shape, sharding,
                    [new_shards[c][q] if c in new_shards
                     else shards[q][c] for c in order],
                )
        return jax.tree.unflatten(treedef, leaves)

    # -- the inner programs ---------------------------------------------------
    @cached_property
    def _program_a(self):
        """The DCN-axis inner phase alone (host-local wrap pairs) — the
        compiled intra-host work the started DCN copies hide behind."""
        ax = (self.axis,)
        fn = jax.shard_map(
            lambda s: self.ex.exchange_blocks(s, axes=ax),
            mesh=self.mesh, in_specs=BLOCK_PSPEC, out_specs=BLOCK_PSPEC,
        )
        return jax.jit(fn, donate_argnums=0)

    @cached_property
    def _program_b(self):
        """The remaining axis phases, run after the DCN apply: their
        full-padded-extent slabs overwrite every stale strip the early
        DCN slabs carried."""
        rest = tuple(p.axis for p in self.plan.axis_phases
                     if p.axis != self.axis)
        fn = jax.shard_map(
            lambda s: self.ex.exchange_blocks(s, axes=rest),
            mesh=self.mesh, in_specs=BLOCK_PSPEC, out_specs=BLOCK_PSPEC,
        )
        return jax.jit(fn, donate_argnums=0)

    # -- one exchange ---------------------------------------------------------
    def __call__(self, state):
        with timer.timed("exchange.hierarchy"), \
                timer.trace_range("exchange.hierarchical"):
            self.last_transfer_count = 0
            self.last_transfer_bytes = 0
            if self._composed:
                return self._overlapped(state)
            return self._sequential(state)

    def _overlapped(self, state):
        """Boundary-first with overlap: start the DCN copies from the
        pre-exchange state, hide them behind the compiled DCN-axis
        phase, apply, then finish the other two phases."""
        pending = self.dcn_start(state)
        state = self._program_a(state)
        state = self.dcn_apply(state, pending)
        return self._program_b(state)

    def _sequential(self, state):
        """Opaque-inner schedule (REMOTE_DMA family): full inner
        exchange first (host-segmented neighbor arithmetic), then one
        post-inner slab per segment boundary fixes the receiver's whole
        DCN-axis halo side — face, edges and corners in one apply."""
        state = self.ex._remote(state)
        pending = self.dcn_start(state)
        return self.dcn_apply(state, pending)

    # -- loops / census -------------------------------------------------------
    def make_loop(self, iters: int):
        """``iters`` back-to-back hierarchical exchanges. A host loop —
        the DCN level is host-orchestrated, so there is no single
        compiled program to fuse (same shape as the REMOTE_DMA
        emulation's loop)."""

        def loop(state):
            for _ in range(iters):
                state = self(state)
            return state

        return loop

    def collective_census(self, state):
        """Census over EVERY compiled piece of one hierarchical
        exchange: the inner programs (whose ppermute count and bytes
        equal the flat plan's — the unchanged inner pin) plus the DCN
        take/update programs (zero collectives by construction)."""
        from ..utils.hlo_check import collective_census

        # run one exchange on a COPY to build every piece: the inner
        # programs donate their inputs, and the caller keeps its state
        self(jax.tree.map(jnp.copy, state))
        total: Dict[str, Tuple[int, int]] = {}

        def merge(census):
            for kind, (c, b) in census.items():
                c0, b0 = total.get(kind, (0, 0))
                total[kind] = (c0 + c, b0 + b)

        if self._composed:
            for prog in (self._program_a, self._program_b):
                merge(collective_census(
                    prog.lower(state).compile().as_text()))
        else:
            merge(self.ex._remote.collective_census(state))
        for key, fn in self._jits.items():
            avals = self._avals.get(key)
            if avals is None:
                continue
            merge(collective_census(
                fn.lower(*avals).compile().as_text()))
        return total
