"""Quadratic-assignment solvers for topology-aware placement.

TPU-native re-implementation of the reference's QAP machinery
(reference: include/stencil/qap.hpp): assign subdomains (with a pairwise
communication-volume matrix ``w``) to devices (with a pairwise distance
matrix ``d``) minimizing ``sum_ab w[a,b] * d[f[a], f[b]]``. Zero times
infinity counts as zero (qap.hpp ``cost_product``), so "no communication"
never pays an infinite-distance penalty.

Two solvers, matching the reference:
- :func:`solve` — exhaustive permutation search in lexicographic order from
  the identity, with a wall-clock timeout (qap.hpp:51-85, 10 s default).
- :func:`solve_catch` — greedy best-pairwise-swap descent with incremental
  cost updates (qap.hpp:87-180).

Both dispatch to the native C++ implementation
(``stencil_tpu/native/qap.cpp``) when the shared library is available —
the exhaustive search is the one compute-heavy host-side component of the
framework, and C++ explores ~100x more permutations within the same
timeout budget. The pure-Python paths remain as a fallback and as the
executable specification.
"""

from __future__ import annotations

import itertools
import time
from typing import List, Sequence, Tuple

import numpy as np

from ..utils import logging as log


def make_reciprocal(m: np.ndarray) -> np.ndarray:
    """Elementwise 1/x (reference: mat2d.hpp:184-199); 1/inf = 0."""
    m = np.asarray(m, dtype=np.float64)
    with np.errstate(divide="ignore"):
        return np.where(np.isinf(m), 0.0, np.divide(1.0, m))


def cost(w: np.ndarray, d: np.ndarray, f: Sequence[int]) -> float:
    """Assignment cost with 0*inf == 0 (reference: qap.hpp cost/cost_product)."""
    w = np.asarray(w, dtype=np.float64)
    d = np.asarray(d, dtype=np.float64)
    f = np.asarray(f, dtype=np.intp)
    dperm = d[np.ix_(f, f)]
    prod = w * dperm
    prod[(w == 0) | (dperm == 0)] = 0.0
    return float(prod.sum())


def solve(
    w: np.ndarray,
    d: np.ndarray,
    timeout_s: float = 10.0,
    use_native: bool = True,
) -> Tuple[List[int], float]:
    """Exhaustive search (timeout-bounded), returns (assignment, cost)."""
    w = np.ascontiguousarray(w, dtype=np.float64)
    d = np.ascontiguousarray(d, dtype=np.float64)
    n = w.shape[0]
    if w.shape != (n, n) or d.shape != (n, n):
        raise ValueError(
            f"weight/distance matrices must both be ({n}, {n}); got "
            f"{w.shape} and {d.shape}"
        )
    if use_native:
        native = _native()
        if native is not None:
            return native.solve(w, d, timeout_s)
    stop = time.monotonic() + timeout_s
    best_f = list(range(n))
    best_cost = cost(w, d, best_f)
    for perm in itertools.permutations(range(n)):
        if time.monotonic() > stop:
            log.warn("qap.solve timed out")
            break
        c = cost(w, d, perm)
        if c < best_cost:
            best_cost = c
            best_f = list(perm)
    return best_f, best_cost


def solve_catch(
    w: np.ndarray, d: np.ndarray, use_native: bool = True
) -> Tuple[List[int], float]:
    """Greedy best-pairwise-swap descent (reference: qap.hpp:87-180).

    Improvements must beat a relative epsilon: the incremental cost update
    accumulates float drift, and on symmetric inputs (many equal-cost
    assignments) drift-sized "improvements" would otherwise cycle forever
    (latent infinite loop in the reference's algorithm)."""
    w = np.ascontiguousarray(w, dtype=np.float64)
    d = np.ascontiguousarray(d, dtype=np.float64)
    n = w.shape[0]
    if w.shape != (n, n) or d.shape != (n, n):
        raise ValueError(
            f"weight/distance matrices must both be ({n}, {n}); got "
            f"{w.shape} and {d.shape}"
        )
    if use_native:
        native = _native()
        if native is not None:
            return native.solve_catch(w, d)

    def pair(a, b, fa, fb):
        we, de = w[a, b], d[fa, fb]
        return 0.0 if (we == 0 or de == 0) else we * de

    best_f = list(range(n))
    best_cost = cost(w, d, best_f)
    improved = True
    while improved:
        improved = False
        impr_f, impr_cost = best_f, best_cost
        for i in range(n):
            for j in range(i + 1, n):
                f = list(best_f)
                c = best_cost
                for k in range(n):
                    c -= pair(i, k, f[i], f[k])
                    c -= pair(j, k, f[j], f[k])
                    if k != i and k != j:
                        c -= pair(k, i, f[k], f[i])
                        c -= pair(k, j, f[k], f[j])
                f[i], f[j] = f[j], f[i]
                for k in range(n):
                    c += pair(i, k, f[i], f[k])
                    c += pair(j, k, f[j], f[k])
                    if k != i and k != j:
                        c += pair(k, i, f[k], f[i])
                        c += pair(k, j, f[k], f[j])
                if c < impr_cost - 1e-12 * (1.0 + abs(impr_cost)):
                    impr_f, impr_cost = f, c
                    improved = True
        if improved:
            best_f, best_cost = impr_f, impr_cost
    return best_f, best_cost


# -- native dispatch ----------------------------------------------------------

_NATIVE = None
_NATIVE_TRIED = False


def _native():
    global _NATIVE, _NATIVE_TRIED
    if not _NATIVE_TRIED:
        _NATIVE_TRIED = True
        try:
            from ..native import qap_native

            _NATIVE = qap_native
        except Exception as e:  # missing .so and no compiler — use Python
            log.debug(f"native qap unavailable ({e}); using Python fallback")
            _NATIVE = None
    return _NATIVE
