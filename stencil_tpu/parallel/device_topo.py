"""Device-interconnect distance discovery.

TPU-native analogue of the reference's NVML-based GPU topology probing
(reference: include/stencil/gpu_topology.hpp, src/gpu_topology.cpp:22-95 —
NVLink/PCIe ancestor-ladder distances 0.1–7.0, bandwidth = 1/distance).

On TPU the interconnect facts come from the device objects themselves:
``device.coords`` gives the chip's position in the physical ICI torus, so
the distance between two chips is their torus hop count; chips in different
processes (hosts) that still share the ICI keep their torus distance, while
devices without coords (CPU/virtual) fall back to process locality. As in
the reference, bandwidth is modeled as 1/distance.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

# distance constants, same spirit as the reference's ladder
# (src/gpu_topology.cpp:22-27): self < linked < same-host < remote
DIST_SELF = 0.1
DIST_SAME_PROCESS = 1.0
DIST_REMOTE = 7.0


def device_distance(a, b) -> float:
    """Hop distance between two JAX devices."""
    if a == b:
        return DIST_SELF
    ca = getattr(a, "coords", None)
    cb = getattr(b, "coords", None)
    if ca is not None and cb is not None and len(ca) == len(cb):
        # ICI torus hops; axis sizes unknown here so use plain manhattan
        # distance (exact for the non-wrapped meshes we can observe)
        hops = sum(abs(int(x) - int(y)) for x, y in zip(ca, cb))
        if hops > 0:
            return float(hops)
    return DIST_SAME_PROCESS if a.process_index == b.process_index else DIST_REMOTE


def distance_matrix(devices: Sequence) -> np.ndarray:
    n = len(devices)
    m = np.zeros((n, n), dtype=np.float64)
    for i, a in enumerate(devices):
        for j, b in enumerate(devices):
            m[i, j] = device_distance(a, b)
    return m


def bandwidth_matrix(devices: Sequence) -> np.ndarray:
    """bandwidth = 1/distance (reference: src/gpu_topology.cpp:95)."""
    return 1.0 / distance_matrix(devices)
