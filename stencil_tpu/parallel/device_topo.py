"""Device-interconnect distance discovery.

TPU-native analogue of the reference's NVML-based GPU topology probing
(reference: include/stencil/gpu_topology.hpp, src/gpu_topology.cpp:22-95 —
NVLink/PCIe ancestor-ladder distances 0.1–7.0, bandwidth = 1/distance).

On TPU the interconnect facts come from the device objects themselves:
``device.coords`` gives the chip's position in the physical ICI torus, so
the distance between two chips is their torus hop count; chips in different
processes (hosts) that still share the ICI keep their torus distance, while
devices without coords (CPU/virtual) fall back to process locality. As in
the reference, bandwidth is modeled as 1/distance.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

# distance constants, same spirit as the reference's ladder
# (src/gpu_topology.cpp:22-27): self < linked < same-host < remote
DIST_SELF = 0.1
DIST_SAME_PROCESS = 1.0
DIST_REMOTE = 7.0

# The virtual-host knob: STENCIL_VIRTUAL_HOSTS=N partitions the single-
# process device list into N emulated hosts whose crossing links take
# the process-boundary cost — the in-process fabric the hierarchical
# (ICI+DCN) exchange, two-level QAP, and 7x link-cost ladder are tested
# on without Gloo CPU collectives.
VIRTUAL_HOSTS_ENV = "STENCIL_VIRTUAL_HOSTS"


def virtual_hosts() -> int:
    """The ``STENCIL_VIRTUAL_HOSTS`` count (0 = knob off)."""
    raw = os.environ.get(VIRTUAL_HOSTS_ENV, "").strip()
    if not raw:
        return 0
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"{VIRTUAL_HOSTS_ENV}={raw!r} is not an integer host count")
    return max(0, n)


def host_assignment(devices: Sequence,
                    hosts: Optional[int] = None) -> List[int]:
    """Per-device host index, aligned with ``devices``.

    With N virtual hosts (``hosts``, defaulting to the env knob), the
    id-SORTED device list splits into N contiguous segments —
    deterministic and placement-invariant: permuting ``devices`` never
    moves a device to a different emulated host, so a placement QAP
    cannot game the fabric it is being priced against. With the knob
    off, a device's host is its real ``process_index``."""
    n = len(devices)
    h = virtual_hosts() if hosts is None else int(hosts)
    if h > 0:
        if n % h:
            raise ValueError(
                f"{h} virtual hosts do not divide {n} devices")
        order = sorted(range(n), key=lambda i: devices[i].id)
        rank = {devices[i].id: r for r, i in enumerate(order)}
        return [rank[d.id] * h // n for d in devices]
    return [int(getattr(d, "process_index", 0)) for d in devices]


def host_groups(devices: Sequence,
                hosts: Optional[int] = None) -> List[list]:
    """Devices grouped by host (ascending host index) — the outer level
    of the hierarchical fabric (real processes, or the virtual-host
    emulation)."""
    assign = host_assignment(devices, hosts)
    groups: dict = {}
    for d, hidx in zip(devices, assign):
        groups.setdefault(hidx, []).append(d)
    return [groups[k] for k in sorted(groups)]


def device_distance(a, b, same_host: Optional[bool] = None) -> float:
    """Hop distance between two JAX devices. ``same_host`` overrides
    the host-locality verdict (the virtual-host fabric: a crossing link
    takes the process-boundary cost even on a single-process mesh);
    ``None`` falls back to the real ``process_index`` comparison."""
    if a == b:
        return DIST_SELF
    if same_host is False:
        # crossing the (possibly emulated) host fabric: the DCN link
        return DIST_REMOTE
    ca = getattr(a, "coords", None)
    cb = getattr(b, "coords", None)
    if ca is not None and cb is not None and len(ca) == len(cb):
        # ICI torus hops; axis sizes unknown here so use plain manhattan
        # distance (exact for the non-wrapped meshes we can observe)
        hops = sum(abs(int(x) - int(y)) for x, y in zip(ca, cb))
        if hops > 0:
            return float(hops)
    if same_host is None:
        same_host = a.process_index == b.process_index
    return DIST_SAME_PROCESS if same_host else DIST_REMOTE


def distance_matrix(devices: Sequence) -> np.ndarray:
    n = len(devices)
    assign = host_assignment(devices)
    m = np.zeros((n, n), dtype=np.float64)
    for i, a in enumerate(devices):
        for j, b in enumerate(devices):
            m[i, j] = device_distance(a, b,
                                      same_host=(assign[i] == assign[j]))
    return m


def bandwidth_matrix(devices: Sequence) -> np.ndarray:
    """bandwidth = 1/distance (reference: src/gpu_topology.cpp:95)."""
    return 1.0 / distance_matrix(devices)
