"""Machine — the cluster model: hosts, processes, and their devices.

TPU-native analogue of the reference's ``Machine`` (reference:
machine.hpp:106-140, src/machine.cpp:72-147), which allgathers hostnames
and GPU UUIDs over MPI to build a global inventory and deduplicate GPUs
visible from multiple ranks. Under JAX the global device list is already
unified — ``jax.devices()`` enumerates every chip of every process with
its owning ``process_index``, so the UUID-dedup machinery is unnecessary;
what remains is the host inventory (gathered with a byte-array allgather
when multi-process, the MPI_Gather analogue of src/machine.cpp:85-101)
and the per-device facts the placement layer consumes.

Note the reference's ``Machine::gpu_distance`` was an unfinished stub
(src/machine.cpp:132); here distances come fully implemented from
``device_topo``.
"""

from __future__ import annotations

import socket
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .device_topo import bandwidth_matrix, distance_matrix

_HOSTNAME_BYTES = 64


@dataclass(frozen=True)
class DeviceInfo:
    """One device's inventory row (reference: machine.cpp per-rank GPU
    records)."""

    index: int
    platform: str
    kind: str
    process_index: int
    coords: Optional[Tuple[int, ...]]
    core_on_chip: Optional[int]


@dataclass
class Machine:
    """Global inventory of processes, hosts, and devices."""

    process_index: int
    process_count: int
    hostnames: Dict[int, str]  # process -> hostname
    devices: List[DeviceInfo] = field(default_factory=list)
    _raw_devices: List = field(default_factory=list, repr=False)

    @classmethod
    def detect(cls, devices: Optional[Sequence] = None) -> "Machine":
        import jax

        raw = list(devices) if devices is not None else jax.devices()
        infos = [
            DeviceInfo(
                index=getattr(d, "id", i),
                platform=d.platform,
                kind=getattr(d, "device_kind", d.platform),
                process_index=d.process_index,
                coords=tuple(d.coords) if getattr(d, "coords", None) is not None else None,
                core_on_chip=getattr(d, "core_on_chip", None),
            )
            for i, d in enumerate(raw)
        ]
        return cls(
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            hostnames=_gather_hostnames(),
            devices=infos,
            _raw_devices=raw,
        )

    # -- queries (reference: machine.hpp:118-139) ---------------------------
    def num_nodes(self) -> int:
        return len(set(self.hostnames.values())) if self.hostnames else 1

    def hostname_of_device(self, info: DeviceInfo) -> str:
        return self.hostnames.get(info.process_index, "?")

    def devices_of_process(self, process: int) -> List[DeviceInfo]:
        return [d for d in self.devices if d.process_index == process]

    def distance_matrix(self) -> np.ndarray:
        return distance_matrix(self._raw_devices)

    def bandwidth_matrix(self) -> np.ndarray:
        return bandwidth_matrix(self._raw_devices)

    def summary(self) -> str:
        """Human-readable dump (the machine-info print,
        reference: bin/machine_info.cu:49-75)."""
        lines = [
            f"machine: {self.num_nodes()} node(s), {self.process_count} "
            f"process(es), {len(self.devices)} device(s)"
        ]
        for p in sorted({d.process_index for d in self.devices}):
            lines.append(f"  process {p} on {self.hostnames.get(p, '?')}:")
            for d in self.devices_of_process(p):
                extra = ""
                if d.coords is not None:
                    extra += f" coords={d.coords}"
                if d.core_on_chip is not None:
                    extra += f" core={d.core_on_chip}"
                lines.append(f"    device {d.index}: {d.platform} ({d.kind}){extra}")
        return "\n".join(lines)


def _gather_hostnames() -> Dict[int, str]:
    """Hostname of every process (MPI_Gather analogue,
    src/machine.cpp:85-101). Single-process: just this host."""
    import jax

    own = socket.gethostname()
    if jax.process_count() == 1:
        return {0: own}
    from jax.experimental import multihost_utils

    buf = np.zeros(_HOSTNAME_BYTES, dtype=np.uint8)
    raw = own.encode()[:_HOSTNAME_BYTES]
    buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(buf)  # (procs, BYTES)
    return {
        p: bytes(gathered[p]).rstrip(b"\x00").decode(errors="replace")
        for p in range(gathered.shape[0])
    }
