from .mesh import AXIS_X, AXIS_Y, AXIS_Z, MESH_AXES, grid_mesh, mesh_dim
from .exchange import BLOCK_PSPEC, Method, HaloExchange, direction_bytes
from .placement import IntraNodeRandom, NodeAware, Placement, Trivial, comm_matrix
from .topology import Boundary, Topology

__all__ = [
    "AXIS_X",
    "AXIS_Y",
    "AXIS_Z",
    "BLOCK_PSPEC",
    "Boundary",
    "HaloExchange",
    "IntraNodeRandom",
    "MESH_AXES",
    "Method",
    "NodeAware",
    "Placement",
    "Topology",
    "Trivial",
    "comm_matrix",
    "direction_bytes",
    "grid_mesh",
    "mesh_dim",
]
