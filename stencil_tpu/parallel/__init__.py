from .mesh import (
    AXIS_X, AXIS_Y, AXIS_Z, BLOCK_PSPEC, MESH_AXES, block_sharding,
    grid_mesh, mesh_dim,
)
from .exchange import Method, HaloExchange, direction_bytes
from .hierarchy import HierarchicalExchange
from .placement import (
    FixedAssignment, IntraNodeRandom, NodeAware, Placement, Trivial,
    comm_matrix,
)
from .topology import Boundary, Topology, link_cost_matrix

__all__ = [
    "AXIS_X",
    "AXIS_Y",
    "AXIS_Z",
    "BLOCK_PSPEC",
    "Boundary",
    "FixedAssignment",
    "HaloExchange",
    "HierarchicalExchange",
    "IntraNodeRandom",
    "MESH_AXES",
    "Method",
    "NodeAware",
    "Placement",
    "Topology",
    "Trivial",
    "block_sharding",
    "comm_matrix",
    "direction_bytes",
    "grid_mesh",
    "link_cost_matrix",
    "mesh_dim",
]
