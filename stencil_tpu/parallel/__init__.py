from .mesh import AXIS_X, AXIS_Y, AXIS_Z, MESH_AXES, grid_mesh, mesh_dim
from .exchange import Method, HaloExchange, direction_bytes

__all__ = [
    "AXIS_X",
    "AXIS_Y",
    "AXIS_Z",
    "MESH_AXES",
    "Method",
    "HaloExchange",
    "direction_bytes",
    "grid_mesh",
    "mesh_dim",
]
