"""26-neighbor periodic halo exchange over a TPU device mesh.

This single module replaces the reference's entire transport zoo — the eight
``Method`` transports, the pack/unpack kernels, the staged/pinned-buffer MPI
state machines, and the CPU polling engine (reference: include/stencil/
method.hpp:5-16, tx_cuda.cuh, tx_colocated.cu, src/stencil.cu:1002-1186).
On TPU all of it collapses into collective permutes compiled by XLA onto the
ICI torus: ``lax.ppermute`` of boundary slabs inside a ``shard_map``-ped,
jitted function (SURVEY.md §5.8). "CUDA graph capture" of the exchange
(packer.cu:96-103) corresponds to the one-time XLA compilation of that jit.

Three exchange strategies are kept (the analogue of the reference's method
selection, src/stencil.cu:372-412):

- ``Method.AXIS_COMPOSED`` (default): three phases, one per axis, two
  ``ppermute``s each. Each phase's slabs span the *full padded extent* of
  the other axes, so edge and corner halos are composed from consecutive
  phases (x fills faces; y slabs carry x-halo data into xy-edges; z slabs
  carry both into xz/yz-edges and corners). 6 collectives total,
  independent of radius shape; supports uneven (remainder) partitions via
  per-device dynamic slab offsets.
- ``Method.DIRECT26``: one ``ppermute`` per active direction (the literal
  translation of the reference's 26 messages) — exact extents on uniform
  partitions; on uneven (remainder) partitions the orthogonal extents are
  padded to the base block size and messages apply in face→edge→corner
  order so every halo cell still ends correct (blocks in the same ring
  share orthogonal-axis sizes, so the valid slab region always aligns).
  Useful for verification and collective-count ablation.
- ``Method.AUTO_SPMD``: NO hand-written collectives at all. The halo fill
  is expressed as a jitted program over the globally-sharded stacked array
  — shifted slices rolled along the *block* dims — and XLA's SPMD
  partitioner synthesizes the collective-permutes. This is the repo's
  analogue of the reference's ``bench_mpi_pack`` question (bin/
  bench_mpi_pack.cu:18-80): does hand-built data-movement machinery beat
  the toolchain's built-in path? Same send-extent rule, periodic wrap,
  radius shapes, uneven partitions, and oversubscription as AXIS_COMPOSED
  (the partitioner turns shard-internal shifts into local copies and
  shard-boundary shifts into permutes on its own); results are required
  bit-identical (tests/test_auto_spmd.py, bench_exchange --ablate).

Send-extent rule pinned from the reference: the data sent toward direction
``d`` fills the receiver's ``-d``-side halo, so its extent is
``halo_extent(-d)`` and a direction is active iff ``radius.dir(-d) != 0``
(reference: src/stencil.cu:344,358-360, test_cuda_local_domain.cu "case1").

Quantity batching (default on, ``batch_quantities=``): a multi-quantity
dict state exchanges per same-dtype group — each collective carries ONE
packed ``(Q, ...slab)`` carrier holding every quantity's boundary slab, so
the collective count per exchange is independent of the quantity count
(6 composed permutes or ≤26 direct ones total, not per quantity). This is
the ``ppermute`` analogue of the reference's multi-quantity per-neighbor
message (packer.cu:10-26) and the answer to the per-collective-overhead
economics the Round-7 ablation measured (DIRECT26 moved 1.9× fewer bytes
but ran 4.2× slower purely on collective count, BASELINE.md).

Every strategy lowers from the declarative ExchangePlan IR
(``stencil_tpu/plan/ir.py``): :attr:`HaloExchange.plan` holds the phase
list (axis phases with permute pairs and size tables; direct26 direction
messages with carrier extents), and the lowering bodies below consume
phase records instead of recomputing the geometry inline. The partition/
method autotuner (``stencil_tpu/plan/``) searches those plans — not code
paths — and this module is required to compile each plan bit-identically
to the historical method branches (census pins in tests/test_plan_ir.py).
"""

from __future__ import annotations

import enum
from functools import cached_property
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding

from ..domain.grid import GridSpec
from ..geometry import DIRECTIONS_26, Dim3, halo_extent
from ..plan.ir import build_plan, spec_axis as _spec_axis
from ..utils import timer
from .mesh import AXIS_X, AXIS_Y, AXIS_Z, BLOCK_PSPEC, block_sharding, mesh_dim


class Method(enum.Enum):
    """Exchange strategy (TPU analogue of method.hpp:5-16)."""

    AXIS_COMPOSED = "axis-composed"
    DIRECT26 = "direct26"
    AUTO_SPMD = "auto-spmd"
    # Kernel-initiated halo exchange (the reference's tx_colocated /
    # ColocatedDirectAccessSender peer-access analogue, §5.8): boundary
    # slabs move as per-neighbor async remote copies issued from INSIDE
    # the kernel (pltpu.make_async_remote_copy), bypassing the XLA
    # collective path — a compiled REMOTE_DMA exchange contains ZERO
    # collective-permutes. On TPU the carrier kernel lives in
    # ops/remote_dma.py; off-TPU a semantics-exact emulation
    # (parallel/remote_emu.py) performs the same per-neighbor copies as
    # host-initiated device-to-device transfers — bit-identical to
    # AXIS_COMPOSED, still zero collectives in every compiled program.
    REMOTE_DMA = "remote-dma"


def direction_bytes(spec: GridSpec, direction, itemsize: int) -> int:
    """Logical bytes received across all blocks for one direction's halos —
    the accounting the reference Allreduces into per-method counters
    (reference: src/stencil.cu:139-161,620-627)."""
    d = Dim3.of(direction)
    if spec.radius.dir(d) == 0:
        return 0
    total = 0
    for iz in range(spec.dim.z):
        for iy in range(spec.dim.y):
            for ix in range(spec.dim.x):
                ext = halo_extent(d, spec.block_size((ix, iy, iz)), spec.radius)
                total += ext.flatten() * itemsize
    return total


class HaloExchange:
    """A compiled halo-exchange over stacked-block arrays.

    State layout: each quantity is an array of shape
    ``(bz, by, bx, pz, py, px)`` sharded ``P('z','y','x')`` over a grid
    mesh; ``__call__`` fills every halo cell whose direction is active and
    returns the updated pytree (donated, so XLA reuses the buffers —
    the in-place halo write of the reference's unpack kernels).

    ``batch_quantities`` (default on): multi-quantity dict states exchange
    per same-dtype GROUP — each collective carries one packed ``(Q, ...)``
    carrier of every quantity's boundary slab, so the collective count per
    exchange is independent of the quantity count (one ``ppermute`` pair
    per composed axis phase / one permute per DIRECT26 direction — the
    multi-quantity message of the reference's DevicePacker, packer.cu:
    10-26, re-expressed for ``lax.ppermute``). ``False`` keeps the
    historical one-collective-per-quantity program (the A/B baseline:
    ``bench_exchange --batched-ab``). Results are bit-identical either
    way — the exchange is pure data movement.
    """

    def __init__(self, spec: GridSpec, mesh: Mesh, method: Method = Method.AXIS_COMPOSED,
                 batch_quantities: bool = True, wire_dtype=None,
                 fused: bool = False, persistent: bool = False,
                 hierarchy=None):
        md = mesh_dim(mesh)
        # oversubscription (reference: dd.set_gpus({0,0}), stencil.hpp:154,
        # test_exchange.cu:52): more partition blocks than devices — the
        # extra blocks are RESIDENT: stacked along the block dims of each
        # shard, exchanged by intra-device slab shifts (see
        # _axis_phase_resident). Any axis may stack (mixed (cz,cy,cx)
        # stacking included) and splits may be uneven — per-resident sizes
        # come from traced lookups into the static per-axis size tables,
        # the same machinery as the dynamic overlap shells (ops/shells.py).
        if spec.dim.x % md.x or spec.dim.y % md.y or spec.dim.z % md.z:
            raise ValueError(
                f"mesh {dict(mesh.shape)} does not divide partition {spec.dim}"
            )
        self.resident = Dim3(
            spec.dim.x // md.x, spec.dim.y // md.y, spec.dim.z // md.z
        )
        self.resident_z = self.resident.z
        for name in (AXIS_X, AXIS_Y, AXIS_Z):
            sizes, rm, rp, _off = _spec_axis(spec, name)
            if min(sizes) < max(rm, rp):
                # halos come from the adjacent block only (one neighbor per
                # direction, like the reference's 26-message plan)
                raise ValueError(
                    f"{name}-axis block size {min(sizes)} < radius {max(rm, rp)}: "
                    "halo would span multiple blocks"
                )
        self.spec = spec
        self.mesh = mesh
        self.method = method
        self.batch_quantities = bool(batch_quantities)
        # hierarchical (ICI+DCN) decomposition (ROADMAP #3): (axis,
        # hosts) of the outer cross-host split, or None for the flat
        # single-level exchange. Validated eagerly — the plan builder is
        # the shape authority, and the method restriction is loud here
        # so a bad PlanChoice fails at construction, not first call.
        if hierarchy is not None:
            from ..plan.ir import validate_hierarchy

            err = validate_hierarchy(hierarchy, md)
            if err is not None:
                raise ValueError(err)
            hierarchy = (str(hierarchy[0]), int(hierarchy[1]))
            if hierarchy[1] > 1 and method not in (
                    Method.AXIS_COMPOSED, Method.REMOTE_DMA):
                raise ValueError(
                    "hierarchical decomposition needs a composed-"
                    "geometry inner method (axis-composed/remote-dma); "
                    f"got {method}"
                )
        self.hierarchy = hierarchy
        # the fused compute+exchange variant (ROADMAP #5): still
        # REMOTE_DMA — kernel-initiated copies, zero ppermutes — but the
        # transport is the concurrent per-direction schedule the fused
        # substep kernels overlap compute behind (plan.fused_phases;
        # ops/fused_stencil.py on TPU, the host-orchestrated
        # FusedRemoteEmulation elsewhere). Single-resident only, loudly.
        self.fused = bool(fused)
        if self.fused:
            if method != Method.REMOTE_DMA:
                raise ValueError(
                    "fused=True is the REMOTE_DMA fused compute+exchange "
                    f"variant; got method {method}"
                )
            if self.resident != Dim3(1, 1, 1):
                raise ValueError(
                    "the fused compute+exchange variant supports "
                    "single-resident partitions only (got resident "
                    f"{self.resident}); use plain REMOTE_DMA or "
                    "AXIS_COMPOSED for oversubscription"
                )
        # the persistent whole-chunk variant (ROADMAP #7): the EXCHANGE is
        # the plain REMOTE_DMA slab transport at the deep radius*k the
        # driver realized — what changes is the step structure (one
        # exchange + ONE whole-chunk program per k-step chunk instead of
        # per step; ops/persistent_stencil.py). The knob exists so the
        # step compilers (ops/jacobi.py) dispatch the chunk loop and the
        # plan carries the launches_per_chunk prediction.
        self.persistent = bool(persistent)
        if self.persistent:
            if method != Method.REMOTE_DMA:
                raise ValueError(
                    "persistent=True is the REMOTE_DMA whole-chunk "
                    f"kernel variant; got method {method}"
                )
            if self.fused:
                raise ValueError(
                    "fused and persistent are mutually exclusive kernel "
                    "variants (the persistent chunk at k == 1 IS the "
                    "fused substep)"
                )
            if self.resident != Dim3(1, 1, 1):
                raise ValueError(
                    "the persistent whole-chunk variant supports "
                    "single-resident partitions only (got resident "
                    f"{self.resident}); use plain REMOTE_DMA or "
                    "AXIS_COMPOSED for oversubscription"
                )
        # launch census (satellite of ROADMAP #7): host-visible program
        # dispatches of the last compiled step loop, per k-step chunk —
        # set by the step compilers, audited against
        # plan.launches_per_chunk (analysis/verify_plan.py)
        self.last_launches_per_chunk: int = 0
        # bf16-on-the-wire halo compression: wire-crossing packed
        # carriers narrow to this dtype before the send and widen on
        # unpack (ops/halo_fill.wire_narrow_dtype owns the policy: only
        # floating carriers ever narrow; local copies stay lossless).
        # Lossy by design — parity gates run with it off; bench_exchange
        # --wire-ab measures the error it buys the bandwidth with.
        if wire_dtype is not None:
            wire_dtype = str(jnp.dtype(wire_dtype))
            if method == Method.AUTO_SPMD:
                from ..utils import logging as log

                log.warn("wire_dtype is ignored for Method.AUTO_SPMD: the "
                         "SPMD partitioner owns the collective schedule "
                         "and packs no carriers")
                wire_dtype = None
        self.wire_dtype = wire_dtype

    @property
    def oversubscribed(self) -> bool:
        """More partition blocks than devices on at least one axis."""
        return self.resident != Dim3(1, 1, 1)

    @property
    def hierarchical(self) -> bool:
        """True when an outer DCN split with more than one host is set
        — the compiled exchange is then the two-level transport
        (parallel/hierarchy.HierarchicalExchange)."""
        return self.hierarchy is not None and self.hierarchy[1] > 1

    def _on_tpu(self) -> bool:
        return all(d.platform == "tpu" for d in self.mesh.devices.flatten())

    @cached_property
    def plan(self):
        """The declarative ExchangePlan this exchange lowers from
        (phases, directions, pack groups, permute pairs — plan/ir.py).
        The autotuner scores these same plans without compiling them."""
        return build_plan(
            self.spec, mesh_dim(self.mesh), self.method,
            batch_quantities=self.batch_quantities, resident=self.resident,
            wire_dtype=self.wire_dtype, fused=self.fused,
            persistent=self.persistent, hierarchy=self.hierarchy,
        )

    # -- public API ----------------------------------------------------------
    def __call__(self, state):
        return self._compiled(state)

    def exchange_block(self, block, axes=None):
        """Per-block exchange body for composing into larger shard_map'd
        steps (e.g. fused compute/exchange overlap): takes and returns one
        (1,1,1,pz,py,px) block inside a ``shard_map`` over this mesh.

        ``axes`` (AXIS_* names) restricts the composed method to a subset of
        axis phases — used by fused kernels that handle self-wrap axes
        internally. Only valid for AXIS_COMPOSED."""
        if self.method == Method.AUTO_SPMD:
            raise RuntimeError(
                "Method.AUTO_SPMD has no per-block exchange body: its "
                "collectives are synthesized by the SPMD partitioner from "
                "the global program (use __call__/make_loop/auto_fill, or a "
                "manual method for shard_map composition)"
            )
        if self.method == Method.REMOTE_DMA:
            raise RuntimeError(
                "Method.REMOTE_DMA has no ppermute-style per-block body: "
                "on TPU the carrier kernel owns the whole phase "
                "(ops/remote_dma.py), and the CPU emulation is "
                "host-orchestrated (use __call__/make_loop, or a manual "
                "ppermute method for shard_map composition)"
            )
        if self.method == Method.DIRECT26:
            if axes is not None:
                raise ValueError("axis subsetting requires AXIS_COMPOSED")
            return self._direct26_blocks(block)
        return self._composed_blocks(block, axes)

    def x_side_buffers(self, block, r: int):
        """Out-of-line x halos for a tight-x layout on a MULTI-BLOCK x axis
        (``Radius.without_x`` with dim.x > 1): the halo columns that would
        live inline are delivered as thin side buffers instead. Returns
        ``(xlo, xhi)``: ``xlo[..., j]`` holds the cell at global
        ``x = x0 - r + j`` (the -x neighbor's top columns), ``xhi[..., j]``
        at ``x0 + nx + j``. Per-block, inside ``shard_map``. The kernels
        roll the interior periodically and the x-edge columns are patched
        from these buffers — the reference's pack-to-buffer transport
        economics (src/pack_kernel.cu:3-54) re-expressed: dense side
        buffers instead of strided inline halo writes."""
        if self.spec.radius.x(-1) != 0 or self.spec.radius.x(1) != 0:
            raise ValueError(
                "x_side_buffers is the tight-x (zero x radius) transport"
            )
        sizes = self.spec.sizes_x
        if len(set(sizes)) != 1:
            raise ValueError("side buffers require a uniform x split")
        if self.resident.x != 1:
            raise ValueError("side buffers do not support x residency")
        n = len(sizes)
        nx = sizes[0]
        hi_cols = block[..., nx - r : nx]
        lo_cols = block[..., 0:r]
        if n > 1:
            fwd = [(i, (i + 1) % n) for i in range(n)]
            bwd = [(i, (i - 1) % n) for i in range(n)]
            return (
                lax.ppermute(hi_cols, AXIS_X, fwd),
                lax.ppermute(lo_cols, AXIS_X, bwd),
            )
        return hi_cols, lo_cols

    def exchange_blocks(self, state, axes=None):
        """Per-block exchange of a whole quantity dict inside ``shard_map``.

        Unlike mapping :meth:`exchange_block` per quantity, the dict is
        processed per same-dtype group (never bitcast): with
        ``batch_quantities`` each collective moves ONE packed ``(Q, ...)``
        carrier of the whole group's boundary slabs — a Q-independent
        collective count per exchange — and fp32 quantities on self-wrap
        axes share the fused multi-quantity fill kernels (the
        multi-quantity-pack analogue, packer.cu:10-26) — one kernel per
        axis phase instead of one per quantity. Non-fp32 groups on
        self-wrap axes take a packed slab fill: one fused slice/update
        pair per phase for the group (the fp64 analogue of the fused
        fills; ROADMAP #5).

        ``axes`` (AXIS_* names) restricts the composed method to a
        subset of axis phases — the hierarchical transport's A/B split
        (DCN-axis phase overlapped behind the started cross-host
        copies, the other phases after the apply). AXIS_COMPOSED only,
        like :meth:`exchange_block`'s ``axes``."""
        if self.method in (Method.AUTO_SPMD, Method.REMOTE_DMA):
            raise RuntimeError(
                f"Method.{self.method.name} has no per-block exchange body "
                "(see exchange_block); use __call__/make_loop instead"
            )
        if axes is not None and self.method != Method.AXIS_COMPOSED:
            raise ValueError("axis subsetting requires AXIS_COMPOSED")
        if not isinstance(state, dict):
            return jax.tree.map(
                lambda b: self.exchange_block(b, axes=axes), state)
        from ..ops.halo_fill import dtype_groups

        groups = dtype_groups(state)
        if self.method == Method.DIRECT26:
            if not self.batch_quantities:
                return jax.tree.map(self.exchange_block, state)
            out = dict(state)
            for _dt, keys in groups:
                blocks = self._direct26_batched([out[k] for k in keys])
                for k, b in zip(keys, blocks):
                    out[k] = b
            return out
        return self._composed_quantities(state, groups, axes)

    def _composed_quantities(self, state, groups, axes=None):
        """AXIS_COMPOSED over a quantity dict, one same-dtype group at a
        time per axis phase: fused Pallas fills for fp32 self-wrap axes,
        packed-carrier phases (one ppermute pair per phase per group)
        elsewhere, per-quantity phases when ``batch_quantities`` is off."""
        from ..ops.halo_fill import max_fill_group

        fills = self._self_fills
        fshape = self._fill_shape()
        gmax = max_fill_group(self.spec) if fills else 0
        out = dict(state)
        for phase in self.plan.axis_phases:
            if not phase.active:
                continue
            if axes is not None and phase.axis not in axes:
                continue
            name = phase.axis
            for dt, keys in groups:
                if phase.blocks == 1 and name in fills and dt == jnp.float32:
                    # only the x kernel's scratch scales with the quantity
                    # count; y/z fills carry every quantity in one kernel
                    ax_gmax = gmax if name == AXIS_X else len(keys)
                    for i in range(0, len(keys), ax_gmax):
                        chunk = keys[i : i + ax_gmax]
                        fill = self._multi_fill(name, len(chunk))
                        res = fill(*[out[k].reshape(fshape) for k in chunk])
                        res = (res,) if len(chunk) == 1 else res
                        for k, v in zip(chunk, res):
                            out[k] = v.reshape(state[k].shape)
                elif self.batch_quantities and len(keys) > 1:
                    blocks = self._axis_phase_batched(
                        [out[k] for k in keys], phase
                    )
                    for k, b in zip(keys, blocks):
                        out[k] = b
                else:
                    for k in keys:
                        out[k] = self._axis_phase(out[k], phase)
        return out

    def _multi_fill(self, axis: str, nq: int):
        cache = self.__dict__.setdefault("_multi_fills", {})
        if (axis, nq) not in cache:
            if nq == 1:
                cache[(axis, nq)] = self._self_fills[axis]
            else:
                from ..ops.halo_fill import make_self_fill
                from .mesh import MESH_AXES

                cache[(axis, nq)] = make_self_fill(
                    self.spec, axis, vma=MESH_AXES, nq=nq,
                    z_stack=self.resident.z,
                )
        return cache[(axis, nq)]

    @cached_property
    def _remote(self):
        """The REMOTE_DMA transport: the Pallas carrier kernels on an
        all-TPU mesh (ops/remote_dma.py — pltpu.make_async_remote_copy
        from inside the kernel), the semantics-exact host-orchestrated
        emulation everywhere else (parallel/remote_emu.py). Both are
        callables over the state pytree; both compile ZERO collectives.
        With ``fused`` the transport is the concurrent per-direction
        schedule instead (ops/fused_stencil.FusedRemoteDmaExchange on
        TPU; FusedRemoteEmulation off it) — same zero-collective pin,
        plus the start/wait split the fused step loops overlap compute
        behind."""
        assert self.method == Method.REMOTE_DMA
        if self._on_tpu():
            if self.fused:
                from ..ops.fused_stencil import FusedRemoteDmaExchange

                return FusedRemoteDmaExchange(self)
            from ..ops.remote_dma import RemoteDmaExchange

            return RemoteDmaExchange(self)
        if self.fused:
            from .remote_emu import FusedRemoteEmulation

            return FusedRemoteEmulation(self)
        from .remote_emu import RemoteDmaEmulation

        return RemoteDmaEmulation(self)

    @cached_property
    def _fused_host_schedule(self):
        """The host-orchestrated start/wait/finish split of the fused
        schedule — what the fused STEP loops bracket their compiled
        sweeps with when the substep is not one mega-kernel. Off-TPU
        this IS :attr:`_remote` (the FusedRemoteEmulation); on a TPU
        mesh :attr:`_remote` is the carrier-kernel transport
        (FusedRemoteDmaExchange — one kernel, no host-visible split),
        so the loops get a separate host-orchestrated instance whose
        ``device_put``s ride between the TPU devices. Requires
        ``fused=True``."""
        if not self.fused:
            raise RuntimeError(
                "_fused_host_schedule requires HaloExchange(fused=True)")
        from .remote_emu import FusedRemoteEmulation

        if not self._on_tpu():
            return self._remote
        return FusedRemoteEmulation(self)

    @cached_property
    def _compiled(self):
        if self.hierarchical:
            # the two-level (ICI+DCN) transport: inner programs stay
            # the lowerings below, the cross-host boundary slabs ride
            # host-orchestrated copies overlapped behind them
            from .hierarchy import HierarchicalExchange

            return HierarchicalExchange(self)
        if self.method == Method.REMOTE_DMA:
            return self._remote
        if self.method == Method.AUTO_SPMD:
            sh = self.sharding()
            return jax.jit(
                lambda state: jax.tree.map(self.auto_fill, state),
                in_shardings=sh, out_shardings=sh, donate_argnums=0,
            )
        fn = jax.shard_map(
            self.exchange_blocks,
            mesh=self.mesh,
            in_specs=BLOCK_PSPEC,
            out_specs=BLOCK_PSPEC,
        )
        return jax.jit(fn, donate_argnums=0)

    def sharding(self) -> NamedSharding:
        return block_sharding(self.mesh)

    def make_loop(self, iters: int):
        """``iters`` back-to-back exchanges in one compiled program — for
        benchmarking without per-dispatch host overhead (the analogue of the
        reference's timed exchange loop, bin/exchange_weak.cu:168-177).
        Loops are cached per ``iters``, so repeated calls reuse the jitted
        program instead of retracing."""
        cache = self.__dict__.setdefault("_loops", {})
        if iters not in cache:
            # build-phase accounting for all strategies (the
            # flight-recorder bucket; jax.profiler sees the same range)
            with timer.timed("exchange.build"), \
                    timer.trace_range(f"exchange.{self.method.value}.build"):
                if self.hierarchical:
                    cache[iters] = self._compiled.make_loop(iters)
                    return cache[iters]
                if self.method == Method.REMOTE_DMA:
                    cache[iters] = self._remote.make_loop(iters)
                    return cache[iters]
                if self.method == Method.AUTO_SPMD:
                    def many(state):
                        return lax.fori_loop(
                            0, iters,
                            lambda _, s: jax.tree.map(self.auto_fill, s), state,
                        )

                    sh = self.sharding()
                    cache[iters] = jax.jit(
                        many, in_shardings=sh, out_shardings=sh,
                        donate_argnums=0,
                    )
                    return cache[iters]

                def many(state):
                    return lax.fori_loop(
                        0, iters, lambda _, s: self.exchange_blocks(s), state
                    )

                fn = jax.shard_map(
                    many, mesh=self.mesh, in_specs=BLOCK_PSPEC,
                    out_specs=BLOCK_PSPEC,
                )
                cache[iters] = jax.jit(fn, donate_argnums=0)
        return cache[iters]

    def collective_census(self, state) -> Dict[str, Tuple[int, int]]:
        """``{op kind: (count, bytes)}`` of ONE compiled exchange of
        ``state`` — the per-method data-movement census the bench_mpi_pack
        ablation tables row out (see utils/hlo_check.collective_census).
        Static counts over the post-SPMD-partitioning HLO: what each
        strategy actually asks the interconnect to move, counted the same
        way for hand-written ppermutes and partitioner-synthesized ones."""
        from ..utils.hlo_check import collective_census

        with timer.timed("exchange.census"), \
                timer.trace_range(f"exchange.{self.method.value}.census"):
            if self.hierarchical:
                # the two-level transport censuses every compiled piece
                # (inner programs + DCN take/updates) — the inner
                # permute count/bytes pin is unchanged, the DCN level
                # contributes zero collectives
                return self._compiled.collective_census(state)
            if self.method == Method.REMOTE_DMA:
                # no single jitted program exists: the transport censuses
                # EVERY compiled piece of one exchange (pack/update jits
                # of the emulation; the carrier-kernel program on TPU) —
                # the 0-ppermute claim is over everything that compiles
                return self._remote.collective_census(state)
            txt = self._compiled.lower(state).compile().as_text()
            return collective_census(txt)

    def bytes_logical(self, itemsizes: Sequence[int]) -> int:
        """Total halo bytes delivered per exchange (reference-parity count)."""
        per_item = sum(
            direction_bytes(self.spec, d, 1) for d in DIRECTIONS_26
        )
        return per_item * sum(itemsizes)

    def bytes_moved(self, itemsizes: Sequence[int]) -> int:
        """Bytes relocated by the exchange implementation: composed slabs
        span full padded extents, so this is >= bytes_logical. On a
        self-wrap (single-block) axis no collective carries data — the same
        slab bytes move in place, via the Pallas fill kernel on TPU (whose
        x/y lane/row-tile RMW amplification is not counted here) or via
        slice+update elsewhere. AUTO_SPMD expresses the composed slab
        program, so it shares the composed accounting (the partitioner may
        move less; collective_census counts what it actually emitted).
        Uneven DIRECT26 pads orthogonal extents to the base block size."""
        p = self.spec.padded()
        if self.method == Method.DIRECT26:
            if self.spec.is_uniform():
                return self.bytes_logical(itemsizes)
            r = self.spec.radius
            b = self.spec.base
            total = 0
            for d in DIRECTIONS_26:
                if r.dir(-d) == 0:
                    continue
                ext = 1
                for dc, rm, rp, base in (
                    (d.z, r.z(-1), r.z(1), b.z),
                    (d.y, r.y(-1), r.y(1), b.y),
                    (d.x, r.x(-1), r.x(1), b.x),
                ):
                    ext *= rm if dc == 1 else rp if dc == -1 else base
                total += ext
            return total * sum(itemsizes) * self.spec.num_blocks()
        per_item = 0
        r = self.spec.radius
        per_item += (r.x(-1) + r.x(1)) * p.y * p.z  # x phase
        per_item += (r.y(-1) + r.y(1)) * p.x * p.z  # y phase
        per_item += (r.z(-1) + r.z(1)) * p.x * p.y  # z phase
        return per_item * sum(itemsizes) * self.spec.num_blocks()

    # -- axis-composed implementation ---------------------------------------
    def _composed_blocks(self, block, axes=None):
        for phase in self.plan.axis_phases:
            if axes is not None and phase.axis not in axes:
                continue
            block = self._axis_phase(block, phase)
        return block

    @cached_property
    def _self_fills(self):
        """axis name -> in-place Pallas halo-fill kernel, for single-block
        (self-wrap) axes on TPU (the pack/unpack-kernel analogue; see
        ops/halo_fill.py). Empty off-TPU or for unsupported layouts.

        Pure z-stack residency ((cz, 1, 1) oversubscription) keeps the
        fills: the x/y kernels act within each z plane, so the stacked
        shard viewed as one (cz*pz, py, px) array is filled by ONE kernel
        (VERDICT r4 item 7 — the reference's same-GPU fast path also runs
        under oversubscription, tx_cuda.cuh:41-113). Mixed x/y residency
        stacks non-z block dims the contiguous reshape can't express —
        those keep the XLA slab path."""
        devs = self.mesh.devices.flatten()
        if not all(d.platform == "tpu" for d in devs):
            return {}
        if self.resident.x != 1 or self.resident.y != 1:
            return {}
        from ..ops.halo_fill import make_self_fill, self_fill_supported
        from .mesh import MESH_AXES

        fills = {}
        for name in (AXIS_X, AXIS_Y, AXIS_Z):
            sizes, _rm, _rp, _o = _spec_axis(self.spec, name)
            if len(sizes) == 1 and self_fill_supported(
                self.spec, name, jnp.float32, z_stack=self.resident.z
            ):
                fills[name] = make_self_fill(
                    self.spec, name, vma=MESH_AXES, z_stack=self.resident.z
                )
        return fills

    def _fill_shape(self) -> Tuple[int, int, int]:
        """The contiguous 3-d view a self-fill kernel runs over: the padded
        block, with any resident z-stack folded into the leading dim."""
        p = self.spec.padded()
        return (self.resident.z * p.z, p.y, p.x)

    def _axis_phase(self, block, phase):
        if not phase.active:
            return block
        if phase.resident > 1:
            return self._axis_phase_resident(block, phase)
        if (
            phase.blocks == 1
            and block.dtype == jnp.float32
            and phase.axis in self._self_fills
        ):
            # self-wrap axis: fill halos in place, touching only the edge
            # tiles, instead of materializing slabs + whole-array updates
            return self._self_fills[phase.axis](
                block.reshape(self._fill_shape())
            ).reshape(block.shape)
        # the slab movement itself is the batched body's Q=1 degeneration
        # (pack_slabs is the identity there) — one copy of the geometry
        return self._axis_phase_batched([block], phase)[0]

    def _resident_sizes(self, name: str, c: int):
        """This device's ``c`` resident block sizes along one axis: static
        ints on a uniform split, traced lookups into the static size table
        otherwise (global block index = axis_index * c + j — jax shards the
        leading block dims in contiguous chunks)."""
        sizes, _rm, _rp, _off = _spec_axis(self.spec, name)
        if len(set(sizes)) == 1:
            return [sizes[0]] * c
        tbl = jnp.asarray(sizes, jnp.int32)
        idx = lax.axis_index(name)
        return [tbl[idx * c + j] for j in range(c)]

    def _axis_phase_resident(self, block, phase):
        """Axis phase with partition blocks resident per device along
        this axis (oversubscription). Neighbor slabs between resident
        blocks shift along the stacked block dim — a pure local copy, the
        analogue of the reference's same-GPU ``PeerAccessSender``
        short-circuit (tx_cuda.cuh:41-113) — and only the two boundary
        slabs ride the collective permute. Works on any axis, uneven
        splits included (per-resident sizes may be traced scalars).
        Implemented as the batched body's Q=1 degeneration."""
        return self._axis_phase_resident_batched([block], phase)[0]

    def _permute_wire(self, carrier, name, pairs):
        """One wire-crossing ``ppermute`` of a packed carrier, paying the
        optional bf16-on-the-wire compression: the carrier narrows to
        ``wire_dtype`` on the send side and widens back after the permute
        (rounding ``astype``, never a bitcast). ONLY data that actually
        crosses the interconnect comes through here — self-wrap copies
        and resident-neighbor shifts never do, so they stay lossless."""
        from ..ops.halo_fill import wire_narrow_dtype

        w = wire_narrow_dtype(carrier.dtype, self.wire_dtype)
        if w is None:
            return lax.ppermute(carrier, name, pairs)
        native = carrier.dtype
        # optimization_barrier on BOTH sides: XLA's convert-mover happily
        # hoists a narrowing convert across a collective-permute (and
        # fuses the pair back into a sender-side rounding), which keeps
        # the rounding but puts full-width bytes back on the wire — the
        # barriers pin narrow-before-send / widen-after-receive so the
        # permute payload (what the census bytes count) really is the
        # wire dtype
        wired = lax.optimization_barrier(carrier.astype(w))
        out = lax.optimization_barrier(lax.ppermute(wired, name, pairs))
        return out.astype(native)

    # -- quantity-batched phases (packed carriers) ---------------------------
    def _axis_phase_batched(self, blocks, phase):
        """One composed axis phase for a same-dtype quantity group: every
        quantity's boundary slab is gathered and stacked into one packed
        ``(Q, ...slab)`` carrier, and ONE ``ppermute`` pair moves the
        whole group — the collective count per phase is independent of Q
        (the DevicePacker's per-neighbor multi-quantity message,
        packer.cu:10-26, as a ppermute payload). Self-wrap axes (n == 1)
        skip the permute: the packed carrier is a single fused slab copy,
        which is also the non-fp32 fill path (fp32 self-wrap axes use the
        Pallas fills upstream). Bit-identical to the per-quantity phases —
        the exchange is pure data movement. Q=1 degenerates to the exact
        historical per-quantity program (pack_slabs is the identity then),
        so :meth:`_axis_phase` delegates here — one copy of the geometry.
        All geometry (size table, permute pairs, radii, offsets) comes
        from the phase record of the ExchangePlan IR."""
        rm, rp, off, adim = phase.rm, phase.rp, phase.offset, phase.adim
        if rm == 0 and rp == 0:
            return blocks
        from ..ops.halo_fill import pack_slabs, unpack_slabs

        if phase.resident > 1:
            return self._axis_phase_resident_batched(blocks, phase)
        name = phase.axis
        n = phase.ring
        if phase.uniform:
            sz = phase.sizes[0]
        else:
            sz = jnp.asarray(phase.sizes, dtype=jnp.int32)[lax.axis_index(name)]
        fwd, bwd = phase.fwd, phase.bwd
        nq = len(blocks)
        if rm > 0:
            carrier = pack_slabs(
                [_slice_in_dim(b, off + sz - rm, rm, adim) for b in blocks]
            )
            if n > 1:  # ONE permute for the whole group
                carrier = self._permute_wire(carrier, name, fwd)
            blocks = [
                _update_in_dim(b, s, off - rm, adim)
                for b, s in zip(blocks, unpack_slabs(carrier, nq))
            ]
        if rp > 0:
            carrier = pack_slabs(
                [_slice_in_dim(b, off, rp, adim) for b in blocks]
            )
            if n > 1:
                carrier = self._permute_wire(carrier, name, bwd)
            blocks = [
                _update_in_dim(b, s, off + sz, adim)
                for b, s in zip(blocks, unpack_slabs(carrier, nq))
            ]
        return blocks

    def _axis_phase_resident_batched(self, blocks, phase):
        """:meth:`_axis_phase_resident` for a same-dtype group:
        resident-neighbor slabs stay per-quantity local copies (they never
        were collectives), and the two boundary slabs of ALL quantities
        ride one packed carrier per ``ppermute`` — still one collective
        pair per phase regardless of Q."""
        from ..ops.halo_fill import pack_slabs, unpack_slabs

        name, adim, bdim = phase.axis, phase.adim, phase.bdim
        rm, rp, off, c = phase.rm, phase.rp, phase.offset, phase.resident
        m = phase.ring
        fwd, bwd = phase.fwd, phase.bwd
        sz = self._resident_sizes(name, c)
        nq = len(blocks)

        def take_j(b, j, start, width):
            starts = _starts(b.ndim, start, adim)
            starts = starts[:bdim] + (jnp.asarray(j, jnp.int32),) + starts[bdim + 1:]
            shp = list(b.shape)
            shp[bdim] = 1
            shp[adim] = width
            return lax.dynamic_slice(b, starts, tuple(shp))

        def put_j(b, slab, j, start):
            starts = _starts(b.ndim, start, adim)
            starts = starts[:bdim] + (jnp.asarray(j, jnp.int32),) + starts[bdim + 1:]
            return lax.dynamic_update_slice(b, slab, starts)

        blocks = list(blocks)
        if rm > 0:
            srcs = [
                [take_j(b, j, off + sz[j] - rm, rm) for j in range(c)]
                for b in blocks
            ]
            incoming = [s[c - 1] for s in srcs]
            if m > 1:
                carrier = self._permute_wire(pack_slabs(incoming), name, fwd)
                incoming = unpack_slabs(carrier, nq)
            for q in range(nq):
                for j in range(c):
                    blocks[q] = put_j(
                        blocks[q], incoming[q] if j == 0 else srcs[q][j - 1],
                        j, off - rm,
                    )
        if rp > 0:
            srcs = [[take_j(b, j, off, rp) for j in range(c)] for b in blocks]
            incoming = [s[0] for s in srcs]
            if m > 1:
                carrier = self._permute_wire(pack_slabs(incoming), name, bwd)
                incoming = unpack_slabs(carrier, nq)
            for q in range(nq):
                for j in range(c):
                    blocks[q] = put_j(
                        blocks[q],
                        incoming[q] if j == c - 1 else srcs[q][j + 1],
                        j, off + sz[j],
                    )
        return blocks

    # -- auto-SPMD implementation -------------------------------------------
    def auto_fill(self, arr):
        """One halo exchange of a stacked GLOBAL array, with no explicit
        collectives: each axis phase slices the send extents and shifts them
        one step along the (sharded) block dim with ``jnp.roll`` — the SPMD
        partitioner decides what actually moves (shard-internal shifts
        become local copies, shard-boundary shifts become
        collective-permutes). Phase order and extents match
        :meth:`_composed_blocks` exactly, so the result is bit-identical to
        AXIS_COMPOSED; corner/edge halos compose across phases the same way.

        Called under ``jax.jit`` on ``P('z','y','x')``-sharded arrays (see
        :attr:`_compiled`); also safe to trace inside larger global jitted
        steps (ops/jacobi.py's AUTO_SPMD path)."""
        for phase in self._auto_plan.axis_phases:
            arr = self._auto_axis_phase(arr, phase)
        return arr

    @cached_property
    def _auto_plan(self):
        """Axis phases in synthesized form (ring spans the FULL per-axis
        block table — the global roll program has no resident concept; the
        partitioner turns shard-internal shifts into local copies on its
        own). :attr:`plan` equals this when the method IS auto-spmd; the
        manual methods still need it for :meth:`auto_fill` composition."""
        if self.method == Method.AUTO_SPMD:
            return self.plan
        return build_plan(
            self.spec, mesh_dim(self.mesh), Method.AUTO_SPMD,
            batch_quantities=self.batch_quantities, resident=self.resident,
        )

    def _auto_axis_phase(self, arr, phase):
        sizes, rm, rp, off = phase.sizes, phase.rm, phase.rp, phase.offset
        if rm == 0 and rp == 0:
            return arr
        adim, bdim = phase.adim, phase.bdim
        n = len(sizes)
        if phase.uniform:
            sz = sizes[0]
            if rm > 0:
                # every block's top rm planes -> its +neighbor's low halo:
                # globally, a roll of the slab one step up the block dim
                slab = lax.slice_in_dim(arr, off + sz - rm, off + sz, axis=adim)
                slab = jnp.roll(slab, 1, axis=bdim)
                arr = _update_in_dim(arr, slab, off - rm, adim)
            if rp > 0:
                slab = lax.slice_in_dim(arr, off, off + rp, axis=adim)
                slab = jnp.roll(slab, -1, axis=bdim)
                arr = _update_in_dim(arr, slab, off + sz, adim)
            return arr
        # uneven axis: per-block source/dest offsets. The source gather and
        # the dest blend are elementwise along (block dim x data dim) pairs,
        # so the partitioner still sees exactly one cross-block movement per
        # side — the roll.
        ndim = arr.ndim
        bshape = [1] * ndim
        bshape[bdim] = n
        sz_b = jnp.asarray(sizes, jnp.int32).reshape(bshape)
        if rm > 0:
            # block i sends [off + sizes[i] - rm, off + sizes[i]); the
            # receiver's low-side halo sits at the static [off - rm, off)
            ashape = [1] * ndim
            ashape[adim] = rm
            gidx = sz_b + (off - rm) + jnp.arange(rm, dtype=jnp.int32).reshape(ashape)
            slab = jnp.take_along_axis(arr, gidx, axis=adim)
            slab = jnp.roll(slab, 1, axis=bdim)
            arr = _update_in_dim(arr, slab, off - rm, adim)
        if rp > 0:
            # the sender side is static ([off, off + rp), the compute
            # origin); the receiver's high-side halo starts at the
            # per-block off + sizes[i] — a masked blend places it
            slab = lax.slice_in_dim(arr, off, off + rp, axis=adim)
            slab = jnp.roll(slab, -1, axis=bdim)
            ashape = [1] * ndim
            ashape[adim] = arr.shape[adim]
            rel = jnp.arange(arr.shape[adim], dtype=jnp.int32).reshape(ashape) - (
                sz_b + off
            )
            vals = jnp.take_along_axis(slab, jnp.clip(rel, 0, rp - 1), axis=adim)
            arr = jnp.where((rel >= 0) & (rel < rp), vals, arr)
        return arr

    # -- direct-26 implementation -------------------------------------------
    def _direct26_blocks(self, block):
        """One quantity's 26-message exchange — the batched body's Q=1
        degeneration (pack_slabs is the identity there), so the direction
        geometry lives in exactly one place."""
        return self._direct26_batched([block])[0]

    def _direct26_batched(self, blocks):
        """DIRECT26 with quantity batching: per active direction, every
        quantity's exact-extent slab packs into one ``(Q, ...)`` carrier
        and ONE permute (or resident roll) moves the whole same-dtype
        group — ≤ 26 collectives per exchange regardless of Q (vs 26·Q
        per-quantity). Q=1 degenerates to the exact historical
        per-quantity program (identity pack, no leading carrier axis) —
        :meth:`_direct26_blocks` delegates here."""
        if not self.spec.is_uniform():
            return self._direct26_batched_uneven(blocks)
        from ..ops.halo_fill import pack_slabs, unpack_slabs

        cz, cy, cx = self.resident.z, self.resident.y, self.resident.x
        nq = len(blocks)
        boff = 1 if nq > 1 else 0  # the packed carrier's leading Q axis
        updates = []
        for ph in self.plan.direct_phases:
            carrier = pack_slabs([
                lax.dynamic_slice(
                    b, (0, 0, 0) + ph.src, (cz, cy, cx) + ph.shape
                )
                for b in blocks
            ])
            carrier = self._roll_blocks(carrier, ph, boff=boff)
            updates.append((carrier, ph.dst))
        out = list(blocks)
        for carrier, dsts in updates:
            for q, piece in enumerate(unpack_slabs(carrier, nq)):
                out[q] = lax.dynamic_update_slice(
                    out[q], piece, (0, 0, 0) + dsts
                )
        return out

    def _direct26_batched_uneven(self, blocks):
        """DIRECT26 on a remainder (uneven) partition: the same 26
        messages, with slab extents padded to the base block size along
        each direction's orthogonal (zero-component) axes — every
        ``ppermute`` participant needs ONE static shape, and blocks in the
        same ring share their orthogonal-axis sizes (grid.py), so the
        valid slab region always aligns sender→receiver. Messages apply in
        face→edge→corner order: a padded write can spill only into a band
        belonging to a direction with MORE nonzero components (or into
        dead pad), so every halo cell's true message lands last — and the
        apply order is preserved per direction across the whole group, so
        the layered-overwrite argument covers packed carriers unchanged.
        Per-block compute extents come from traced lookups into the static
        per-axis size tables, the same machinery as
        :meth:`_axis_phase_resident` (VERDICT r5 "Next" #5; ROADMAP #4).
        Q=1 degenerates to the per-quantity program (identity pack)."""
        from ..ops.halo_fill import pack_slabs, unpack_slabs

        spec = self.spec
        r = spec.radius
        off = spec.compute_offset()
        base = spec.base
        cz, cy, cx = self.resident.z, self.resident.y, self.resident.x
        sz = {
            AXIS_Z: self._resident_sizes(AXIS_Z, cz),
            AXIS_Y: self._resident_sizes(AXIS_Y, cy),
            AXIS_X: self._resident_sizes(AXIS_X, cx),
        }
        nq = len(blocks)
        boff = 1 if nq > 1 else 0  # the packed carrier's leading Q axis
        out = list(blocks)
        # plan phases arrive pre-sorted face -> edge -> corner with zero-
        # extent directions dropped and base-padded static carrier shapes
        for ph in self.plan.direct_phases:
            d = Dim3.of(ph.direction)
            info = tuple(zip(
                (d.z, d.y, d.x),
                (off.z, off.y, off.x),
                (r.z(-1), r.y(-1), r.x(-1)),
                (r.z(1), r.y(1), r.x(1)),
                (base.z, base.y, base.x),
            ))
            shape = ph.shape

            def gather(block):
                parts_z = []
                for jz in range(cz):
                    parts_y = []
                    for jy in range(cy):
                        parts_x = []
                        for jx in range(cx):
                            s3 = (sz[AXIS_Z][jz], sz[AXIS_Y][jy], sz[AXIS_X][jx])
                            src = tuple(
                                o + s - rm if dc == 1 else o
                                for (dc, o, rm, _rp, _b), s in zip(info, s3)
                            )
                            parts_x.append(lax.dynamic_slice(
                                block, _starts6((jz, jy, jx), src),
                                (1, 1, 1) + shape,
                            ))
                        parts_y.append(_concat(parts_x, 2))
                    parts_z.append(_concat(parts_y, 1))
                return _concat(parts_z, 0)

            carrier = self._roll_blocks(
                pack_slabs([gather(b) for b in out]), ph, boff=boff
            )
            for q, slab in enumerate(unpack_slabs(carrier, nq)):
                for jz in range(cz):
                    for jy in range(cy):
                        for jx in range(cx):
                            s3 = (sz[AXIS_Z][jz], sz[AXIS_Y][jy], sz[AXIS_X][jx])
                            dst = tuple(
                                o - rm if dc == 1 else o + s if dc == -1 else o
                                for (dc, o, rm, _rp, _b), s in zip(info, s3)
                            )
                            piece = lax.dynamic_slice(
                                slab, _starts6((jz, jy, jx), (0, 0, 0)),
                                (1, 1, 1) + shape,
                            )
                            out[q] = lax.dynamic_update_slice(
                                out[q], piece, _starts6((jz, jy, jx), dst)
                            )
        return out

    def _roll_blocks(self, slab, ph, boff: int = 0):
        """Send each resident block's slab to its ``+direction`` neighbor
        in the GLOBAL block grid: without oversubscription this is the
        single diagonal 26-neighbor permute (the phase record carries the
        flattened pairs); with residents each axis shifts the stacked
        block dim locally and only the wrap-around boundary rides an axis
        permute (the per-axis composition of the same move). ``boff``:
        leading batch axes before the block dims (the packed ``(Q, ...)``
        carrier of the quantity-batched path)."""
        d = Dim3.of(ph.direction)
        if not self.oversubscribed:
            return self._permute_wire(slab, (AXIS_Z, AXIS_Y, AXIS_X), ph.pairs)
        md = mesh_dim(self.mesh)
        for name, bdim, comp, m, c in (
            (AXIS_Z, boff + 0, d.z, md.z, self.resident.z),
            (AXIS_Y, boff + 1, d.y, md.y, self.resident.y),
            (AXIS_X, boff + 2, d.x, md.x, self.resident.x),
        ):
            if comp == 0:
                continue
            if c == 1:
                if m > 1:
                    pairs = [(i, (i + comp) % m) for i in range(m)]
                    slab = self._permute_wire(slab, name, pairs)
                continue
            if comp == 1:
                last = lax.slice_in_dim(slab, c - 1, c, axis=bdim)
                if m > 1:
                    last = self._permute_wire(
                        last, name, [(i, (i + 1) % m) for i in range(m)])
                slab = jnp.concatenate(
                    [last, lax.slice_in_dim(slab, 0, c - 1, axis=bdim)], axis=bdim
                )
            else:
                first = lax.slice_in_dim(slab, 0, 1, axis=bdim)
                if m > 1:
                    first = self._permute_wire(
                        first, name, [(i, (i - 1) % m) for i in range(m)])
                slab = jnp.concatenate(
                    [lax.slice_in_dim(slab, 1, c, axis=bdim), first], axis=bdim
                )
        return slab

def _starts(ndim: int, start, adim: int):
    """Per-dim start indices, uniformly int32 (mixed Python-int / traced-scalar
    starts trip dynamic_slice's same-dtype requirement under x64)."""
    s = [jnp.asarray(0, jnp.int32)] * ndim
    s[adim] = jnp.asarray(start, jnp.int32)
    return tuple(s)


def _starts6(bidx, data_starts):
    """Start indices of one resident block's slab in the stacked layout:
    (jz, jy, jx) block dims + (z, y, x) data starts, uniformly int32
    (data starts may be traced size-table lookups)."""
    return tuple(jnp.asarray(v, jnp.int32) for v in (*bidx, *data_starts))


def _concat(parts, axis: int):
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=axis)


def _slice_in_dim(block, start, width: int, adim: int):
    """dynamic_slice along one data dim of a (1,1,1,pz,py,px) block."""
    sizes = list(block.shape)
    sizes[adim] = width
    return lax.dynamic_slice(block, _starts(block.ndim, start, adim), tuple(sizes))


def _update_in_dim(block, slab, start, adim: int):
    return lax.dynamic_update_slice(block, slab, _starts(block.ndim, start, adim))


# -- host <-> stacked-block conversion ---------------------------------------

def shard_blocks(
    global_zyx: np.ndarray, spec: GridSpec, mesh: Mesh, dtype=None
) -> jax.Array:
    """Scatter a global [z,y,x] host array into the stacked padded layout.

    Halo and pad-tail cells are zero-initialized (garbage until the first
    exchange, like fresh cudaMalloc in local_domain.cu:159-220).
    """
    g = spec.global_size
    if global_zyx.shape != (g.z, g.y, g.x):
        raise ValueError(
            f"global array shape {global_zyx.shape} != grid "
            f"({g.z}, {g.y}, {g.x})"
        )
    stacked = np.zeros(spec.stacked_shape_zyx(), dtype=dtype or global_zyx.dtype)
    off = spec.compute_offset()
    for iz in range(spec.dim.z):
        for iy in range(spec.dim.y):
            for ix in range(spec.dim.x):
                o = spec.block_origin((ix, iy, iz))
                s = spec.block_size((ix, iy, iz))
                stacked[
                    iz, iy, ix,
                    off.z : off.z + s.z,
                    off.y : off.y + s.y,
                    off.x : off.x + s.x,
                ] = global_zyx[o.z : o.z + s.z, o.y : o.y + s.y, o.x : o.x + s.x]
    return jax.device_put(jnp.asarray(stacked), NamedSharding(mesh, BLOCK_PSPEC))


def unshard_blocks(stacked, spec: GridSpec) -> np.ndarray:
    """Gather the compute regions of a stacked array back into a global
    [z,y,x] host array (halos dropped)."""
    g = spec.global_size
    arr = np.asarray(jax.device_get(stacked))
    out = np.empty((g.z, g.y, g.x), dtype=arr.dtype)
    off = spec.compute_offset()
    for iz in range(spec.dim.z):
        for iy in range(spec.dim.y):
            for ix in range(spec.dim.x):
                o = spec.block_origin((ix, iy, iz))
                s = spec.block_size((ix, iy, iz))
                out[o.z : o.z + s.z, o.y : o.y + s.y, o.x : o.x + s.x] = arr[
                    iz, iy, ix,
                    off.z : off.z + s.z,
                    off.y : off.y + s.y,
                    off.x : off.x + s.x,
                ]
    return out
