"""Semantics-exact CPU emulation of the REMOTE_DMA halo exchange.

``Method.REMOTE_DMA``'s real transport issues per-neighbor async remote
copies from inside the compute kernel (``pltpu.make_async_remote_copy``,
ops/remote_dma.py) — data movement the XLA collective path never sees.
This container's jax (0.4.37) has no TPU and no Pallas cross-device
interpret mode, so correctness is pinned here instead: the SAME
per-neighbor copy schedule, executed as host-initiated device-to-device
transfers (``jax.device_put`` of the packed boundary carrier straight to
the neighbor device — the closest thing a CPU backend has to a remote
DMA: a point-to-point copy that no collective compiler arbitrates).

Each axis phase (composed x→y→z geometry, straight from the plan's
``RemoteDmaPhaseIR`` records) runs as three stages:

1. **take** (compiled per device, ZERO collectives): slice the boundary
   slabs of the device's resident stack and pack the same-dtype group
   into one ``(Q, …slab)`` carrier (PR-5 geometry — the transfer count
   is Q-independent), narrowing to ``wire_dtype`` when the bf16-on-the-
   wire knob is set;
2. **transfer** (no program at all): ``device_put`` each carrier to its
   ring neighbor — the emulated remote DMA (a self-wrap ring degenerates
   to a local hand-off, exactly like the kernel's loopback copy);
3. **update** (compiled per device, ZERO collectives): widen + unpack
   the received carriers and write every halo slab — the incoming
   boundary plus the resident-neighbor shifts, which never left the
   device (the same split ``_axis_phase_resident_batched`` lowers).

Because a halo exchange is pure data movement, copying the same cells
makes the result bit-identical to ``AXIS_COMPOSED`` by construction —
tests/test_remote_dma.py pins it across uniform/uneven/oversubscribed
partitions and mixed-dtype states. ``collective_census`` here censuses
EVERY compiled piece of one exchange; the pinned verdict is 0
collective-permutes (the REMOTE_DMA claim, honest on both lowerings).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.halo_fill import pack_slabs, unpack_slabs, wire_narrow_dtype
from ..utils import timer


class RemoteDmaEmulation:
    """Host-orchestrated REMOTE_DMA lowering for non-TPU meshes."""

    def __init__(self, ex):
        from .exchange import HaloExchange  # noqa: F401 — typing only

        self.ex = ex
        self.mesh = ex.mesh
        self.plan = ex.plan
        if jax.process_count() > 1:
            raise NotImplementedError(
                "the REMOTE_DMA CPU emulation is single-process (every "
                "shard must be addressable for host-initiated neighbor "
                "copies); multi-host REMOTE_DMA is the TPU kernel's job"
            )
        # mesh coords per device: mesh.devices is (mz, my, mx) in the
        # ('z', 'y', 'x') axis order of parallel/mesh.py
        self._coords: Dict[int, Tuple[int, int, int]] = {}
        md = self.mesh.devices
        for iz in range(md.shape[0]):
            for iy in range(md.shape[1]):
                for ix in range(md.shape[2]):
                    self._coords[md[iz, iy, ix].id] = (iz, iy, ix)
        self._jits: Dict[tuple, object] = {}
        self._avals: Dict[tuple, tuple] = {}
        self.last_transfer_count = 0  # emulated remote copies, last exchange

    # -- compiled pieces ------------------------------------------------------
    def _jit(self, key, build):
        """Cache one jitted piece per static geometry key, remembering
        its argument avals so :meth:`collective_census` can lower it."""
        if key not in self._jits:
            self._jits[key] = jax.jit(build())
        return self._jits[key]

    def _remember(self, key, args) -> None:
        if key not in self._avals:
            self._avals[key] = tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args
            )

    def _device_sizes(self, phase, i: int) -> Tuple[int, ...]:
        c = phase.resident
        return tuple(int(phase.sizes[i * c + j]) for j in range(c))

    def _seg_wrap(self, axis: str, i: int, step: int, m: int) -> int:
        """Ring neighbor ``i + step`` along ``axis`` — wrapping within
        the host segment instead of the full ring when the plan is
        hierarchical on that axis (the emulated-DMA twin of the plan's
        ``_segmented_ring_pairs``): the inner transport then never
        reaches across a host, and the boundary slabs ride the DCN
        level (parallel/hierarchy.py) instead."""
        h = self.plan.hierarchy
        if h is not None and h[1] > 1 and h[0] == axis:
            seg = m // h[1]
            base = (i // seg) * seg
            return base + (i - base + step) % seg
        return (i + step) % m

    def _take_fn(self, phase, sizes, shard_shape, dtype, nq, wire):
        """take(*shards) -> (hi_carrier?, lo_carrier?): the boundary
        slabs this device sends (+axis: its LAST resident's top rm slab;
        -axis: its FIRST resident's bottom rp slab), packed per group and
        narrowed to the wire dtype when compression is on."""
        rm, rp, off, adim, bdim, c = (phase.rm, phase.rp, phase.offset,
                                      phase.adim, phase.bdim, phase.resident)
        sz_last = sizes[c - 1]

        def slab(s, j, start, width):
            idx = [slice(None)] * len(shard_shape)
            idx[bdim] = slice(j, j + 1)
            idx[adim] = slice(start, start + width)
            return s[tuple(idx)]

        def take(*shards):
            out = []
            if rm:
                hi = pack_slabs([slab(s, c - 1, off + sz_last - rm, rm)
                                 for s in shards])
                out.append(hi.astype(wire) if wire is not None else hi)
            if rp:
                lo = pack_slabs([slab(s, 0, off, rp) for s in shards])
                out.append(lo.astype(wire) if wire is not None else lo)
            return tuple(out)

        return take

    def _update_fn(self, phase, sizes, shard_shape, dtype, nq, wire):
        """update(*shards, recv...) -> new shards: write every halo slab
        of this device's resident stack — lane 0's low halo from the
        received -axis carrier, lane c-1's high halo from the +axis one,
        interior lanes from their resident neighbors (local, lossless)."""
        rm, rp, off, adim, bdim, c = (phase.rm, phase.rp, phase.offset,
                                      phase.adim, phase.bdim, phase.resident)

        def slab(s, j, start, width):
            idx = [slice(None)] * len(shard_shape)
            idx[bdim] = slice(j, j + 1)
            idx[adim] = slice(start, start + width)
            return s[tuple(idx)]

        def put(s, piece, j, start, width):
            idx = [slice(None)] * len(shard_shape)
            idx[bdim] = slice(j, j + 1)
            idx[adim] = slice(start, start + width)
            return s.at[tuple(idx)].set(piece)

        def update(*args):
            shards = list(args[:nq])
            rest = list(args[nq:])
            recv_lo = recv_hi = None
            if rm:
                recv_lo = rest.pop(0)
                if wire is not None:
                    recv_lo = recv_lo.astype(dtype)
            if rp:
                recv_hi = rest.pop(0)
                if wire is not None:
                    recv_hi = recv_hi.astype(dtype)
            lo_q = unpack_slabs(recv_lo, nq) if rm else None
            hi_q = unpack_slabs(recv_hi, nq) if rp else None
            out = []
            for q, s in enumerate(shards):
                o = s
                if rm:
                    for j in range(c):
                        piece = (lo_q[q] if j == 0 else
                                 slab(s, j - 1, off + sizes[j - 1] - rm, rm))
                        o = put(o, piece, j, off - rm, rm)
                if rp:
                    for j in range(c):
                        piece = (hi_q[q] if j == c - 1 else
                                 slab(s, j + 1, off, rp))
                        o = put(o, piece, j, off + sizes[j], rp)
                out.append(o)
            return tuple(out)

        return update

    # -- one exchange ---------------------------------------------------------
    def _phase_groups(self, leaves) -> List[Tuple[object, List[int]]]:
        """Same-dtype leaf groups in first-appearance order (PR-5's
        packing unit); per-leaf groups when batching is off — the
        transfer count then scales with Q, like the per-quantity
        ppermute program it mirrors."""
        if not self.ex.batch_quantities:
            return [(leaves[i].dtype, [i]) for i in range(len(leaves))]
        groups: Dict[object, List[int]] = {}
        for i, leaf in enumerate(leaves):
            groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
        return list(groups.items())

    def _shards_by_coords(self, leaf):
        out = {}
        for sh in leaf.addressable_shards:
            out[self._coords[sh.device.id]] = sh.data
        return out

    def __call__(self, state):
        with timer.timed("exchange.remote_emu"), \
                timer.trace_range("exchange.remote-dma.emulated"):
            return self._exchange_once(state)

    def _exchange_once(self, state):
        leaves, treedef = jax.tree.flatten(state)
        self.last_transfer_count = 0
        sharding = self.ex.sharding()
        for phase in self.plan.remote_phases:
            if not phase.active:
                continue
            leaves = self._run_phase(leaves, phase, sharding)
        return jax.tree.unflatten(treedef, leaves)

    def _run_phase(self, leaves, phase, sharding):
        mdevs = self.mesh.devices
        axis_of = {"z": 0, "y": 1, "x": 2}[phase.axis]
        m = phase.ring
        leaves = list(leaves)
        for dtype, idxs in self._phase_groups(leaves):
            nq = len(idxs)
            # only wire-crossing carriers compress (ring > 1): a
            # self-wrap phase's hand-off never leaves the device and
            # stays lossless, matching the composed lowering's policy
            wire = (wire_narrow_dtype(dtype, self.ex.wire_dtype)
                    if m > 1 else None)
            shards = [self._shards_by_coords(leaves[i]) for i in idxs]
            coords_list = list(shards[0])
            # 1. take: pack each device's outbound boundary carriers
            sent: Dict[Tuple[int, int, int], tuple] = {}
            for coords in coords_list:
                i = coords[axis_of]
                sizes = self._device_sizes(phase, i)
                args = tuple(s[coords] for s in shards)
                key = ("take", phase.axis, sizes, args[0].shape,
                       str(dtype), nq, str(wire))
                fn = self._jit(key, lambda: self._take_fn(
                    phase, sizes, args[0].shape, dtype, nq, wire))
                self._remember(key, args)
                sent[coords] = fn(*args)
            # 2. transfer: each carrier rides straight to its ring
            # neighbor — the emulated per-neighbor remote DMA (self-wrap
            # rings hand the carrier back to the same device)
            recv: Dict[Tuple[int, int, int], list] = {c: [] for c in coords_list}
            for coords in coords_list:
                i = coords[axis_of]
                out = list(sent[coords])
                if phase.rm:
                    # +axis send: this device's top slab fills the low
                    # halo of ring neighbor i+1 (the composed fwd pair;
                    # host-segmented when the plan is hierarchical)
                    dst = list(coords)
                    dst[axis_of] = self._seg_wrap(phase.axis, i, 1, m)
                    dst = tuple(dst)
                    carrier = out.pop(0)
                    if dst != coords:
                        carrier = jax.device_put(carrier, mdevs[dst])
                        self.last_transfer_count += 1
                    recv[dst].insert(0, ("lo", carrier))
                if phase.rp:
                    dst = list(coords)
                    dst[axis_of] = self._seg_wrap(phase.axis, i, -1, m)
                    dst = tuple(dst)
                    carrier = out.pop(0)
                    if dst != coords:
                        carrier = jax.device_put(carrier, mdevs[dst])
                        self.last_transfer_count += 1
                    recv[dst].append(("hi", carrier))
            # 3. update: write every halo slab from the received
            # carriers + the local resident-neighbor shifts
            new_shards: Dict[Tuple[int, int, int], tuple] = {}
            for coords in coords_list:
                i = coords[axis_of]
                sizes = self._device_sizes(phase, i)
                args = tuple(s[coords] for s in shards)
                carriers = [c for tag, c in sorted(
                    recv[coords], key=lambda t: 0 if t[0] == "lo" else 1)]
                key = ("upd", phase.axis, sizes, args[0].shape,
                       str(dtype), nq, str(wire))
                fn = self._jit(key, lambda: self._update_fn(
                    phase, sizes, args[0].shape, dtype, nq, wire))
                self._remember(key, tuple(args) + tuple(carriers))
                new_shards[coords] = fn(*args, *carriers)
            # reassemble each leaf from its updated shards
            order = [self._coords[d.id] for d in mdevs.flat]
            for q, li in enumerate(idxs):
                leaves[li] = jax.make_array_from_single_device_arrays(
                    leaves[li].shape, sharding,
                    [new_shards[c][q] for c in order],
                )
        return leaves

    # -- loops / census -------------------------------------------------------
    def make_loop(self, iters: int):
        """``iters`` back-to-back exchanges. A host loop (the emulation
        has no single compiled program to fuse) — correct, not fast; the
        fused-loop economics belong to the TPU carrier kernel."""

        def loop(state):
            for _ in range(iters):
                state = self(state)
            return state

        return loop

    def collective_census(self, state) -> Dict[str, Tuple[int, int]]:
        """Census over EVERY compiled piece one exchange of ``state``
        runs (all take/update programs): op counts summed across pieces.
        The REMOTE_DMA pin is that this comes back with no
        ``collective-permute`` entry at all."""
        from ..utils.hlo_check import collective_census

        # make sure every piece this state needs exists (and is recorded)
        self._exchange_once(state)
        total: Dict[str, Tuple[int, int]] = {}
        for key, fn in self._jits.items():
            avals = self._avals.get(key)
            if avals is None:
                continue
            txt = fn.lower(*avals).compile().as_text()
            for kind, (c, b) in collective_census(txt).items():
                c0, b0 = total.get(kind, (0, 0))
                total[kind] = (c0 + c, b0 + b)
        return total


class FusedRemoteEmulation(RemoteDmaEmulation):
    """Host-orchestrated FUSED compute+exchange schedule (ROADMAP #5).

    The fused mega-kernel's order — (1) pack boundary slabs and START
    every per-neighbor copy boundary-first, (2) compute interior tiles
    while the DMAs fly, (3) wait the recv semaphores, (4) compute the
    boundary tiles — executed host-side for non-TPU meshes, with the
    caller owning steps 2 and 4 (``_compile_jacobi_fused`` /
    ``make_fused_astaroth_loop`` slot their compiled collective-free
    sweeps between :meth:`fused_start` and :meth:`fused_finish`).

    The composed x→y→z slab geometry cannot start boundary-first (a y
    slab carries x-halo data, so phase y's send depends on phase x's
    receive); the fused schedule therefore moves one EXACT-extent
    message per active direction — the plan's ``FusedPhaseIR`` records,
    the DIRECT26 geometry re-transported. Every message reads only
    sender compute-region cells, so all of them start concurrently, and
    together they fill every declared halo cell bit-identically to
    AXIS_COMPOSED (the same data-movement argument that pins DIRECT26;
    tests/test_fused_stencil.py pins it here, wire compression
    included — a carrier rounds exactly once either way). Every compiled
    piece (per-device take/update programs) censuses ZERO
    collective-permutes, the same pin as the serialized emulation."""

    def __init__(self, ex):
        from ..geometry import Dim3

        super().__init__(ex)
        if ex.resident != Dim3(1, 1, 1):
            raise ValueError(
                "the fused compute+exchange schedule supports "
                "single-resident partitions only (got resident "
                f"{ex.resident}); use the plain REMOTE_DMA carrier or "
                "AXIS_COMPOSED for oversubscription"
            )
        if not self.plan.fused:
            raise RuntimeError(
                "fused emulation needs a fused plan (HaloExchange built "
                "without fused=True?)"
            )

    # -- geometry -------------------------------------------------------------
    def _block_sizes(self, coords) -> Tuple[int, int, int]:
        iz, iy, ix = coords
        s = self.ex.spec.block_size((ix, iy, iz))
        return (s.z, s.y, s.x)

    def _dir_slices(self, sizes, outbound: bool):
        """Per-phase static (z, y, x) slices into a padded shard: the
        outbound compute-region slab a device sends toward each
        direction, or the halo region the received carrier fills —
        exact extents, so no write overlaps another (no layering
        needed). ``sizes`` are THIS device's block sizes (ring-sharing
        makes the orthogonal extents match the sender's)."""
        spec = self.ex.spec
        r = spec.radius
        off = spec.compute_offset()
        out = []
        for ph in self.plan.fused_phases:
            dx, dy, dz = ph.direction
            sl = [slice(None), slice(None), slice(None)]
            for i, (dc, s, rmin, rplus, o) in enumerate(zip(
                (dz, dy, dx), sizes,
                (r.z(-1), r.y(-1), r.x(-1)),
                (r.z(1), r.y(1), r.x(1)),
                (off.z, off.y, off.x),
            )):
                if dc == 1:
                    sl.append(slice(o + s - rmin, o + s) if outbound
                              else slice(o - rmin, o))
                elif dc == -1:
                    sl.append(slice(o, o + rplus) if outbound
                              else slice(o + s, o + s + rplus))
                else:
                    sl.append(slice(o, o + s))
            out.append((tuple(sl), ph.crossing))
        return out

    def _fused_take_fn(self, sizes, shard_shape, dtype, nq, wire):
        """take(*shards) -> one packed carrier per direction (phase
        order), narrowed to the wire dtype on wire-crossing directions
        (self-wrap hand-offs stay lossless — the composed policy)."""
        specs = self._dir_slices(sizes, outbound=True)

        def take(*shards):
            out = []
            for sl, crossing in specs:
                car = pack_slabs([s[sl] for s in shards])
                if wire is not None and crossing:
                    car = car.astype(wire)
                out.append(car)
            return tuple(out)

        return take

    def _fused_update_fn(self, sizes, shard_shape, dtype, nq, wire):
        """update(*shards, *carriers) -> new shards: widen + unpack every
        received carrier into its exact halo region."""
        specs = self._dir_slices(sizes, outbound=False)

        def update(*args):
            shards = list(args[:nq])
            carriers = args[nq:]
            for (sl, crossing), car in zip(specs, carriers):
                if wire is not None and crossing:
                    car = car.astype(dtype)
                for q, slab in enumerate(unpack_slabs(car, nq)):
                    shards[q] = shards[q].at[sl].set(slab)
            return tuple(shards)

        return update

    # -- the fused schedule ---------------------------------------------------
    def fused_start(self, state):
        """Stages 1+2: pack every device's per-direction carriers
        (compiled takes, zero collectives) and START the emulated remote
        copies — ``device_put`` toward the neighbor, issued but not
        synced, so the caller's interior compute dispatches while they
        fly. Returns the pending structure for :meth:`fused_wait` /
        :meth:`fused_finish`."""
        leaves, treedef = jax.tree.flatten(state)
        self.last_transfer_count = 0
        mdevs = self.mesh.devices
        mz, my, mx = mdevs.shape
        phases = self.plan.fused_phases
        pending = {"treedef": treedef, "leaves": leaves,
                   "sharding": self.ex.sharding(), "groups": []}
        for dtype, idxs in self._phase_groups(leaves):
            nq = len(idxs)
            wire = wire_narrow_dtype(dtype, self.ex.wire_dtype)
            shards = [self._shards_by_coords(leaves[i]) for i in idxs]
            coords_list = list(shards[0])
            recv: Dict[Tuple[int, int, int], list] = {
                c: [None] * len(phases) for c in coords_list}
            for coords in coords_list:
                sizes = self._block_sizes(coords)
                args = tuple(s[coords] for s in shards)
                key = ("ftake", sizes, args[0].shape, str(dtype), nq,
                       str(wire))
                fn = self._jit(key, lambda: self._fused_take_fn(
                    sizes, args[0].shape, dtype, nq, wire))
                self._remember(key, args)
                carriers = fn(*args)
                iz, iy, ix = coords
                for pi, ph in enumerate(phases):
                    dx, dy, dz = ph.direction
                    # host-segmented on the DCN axis under a hierarchy:
                    # no fused message crosses a host — the boundary
                    # slabs ride the DCN level, whose full-extent apply
                    # overwrites every garbage wrap cell (face+edge+
                    # corner, all confined to the DCN-axis halo)
                    dst = (self._seg_wrap("z", iz, dz, mz),
                           self._seg_wrap("y", iy, dy, my),
                           self._seg_wrap("x", ix, dx, mx))
                    car = carriers[pi]
                    if dst != coords:
                        car = jax.device_put(car, mdevs[dst])
                        self.last_transfer_count += 1
                    recv[dst][pi] = car
            pending["groups"].append((dtype, idxs, shards, recv))
        return pending

    def fused_wait(self, pending) -> None:
        """Stage 3: the recv-semaphore wait — block until every started
        carrier has landed on its destination device."""
        for _dt, _idxs, _shards, recv in pending["groups"]:
            for per_dev in recv.values():
                for car in per_dev:
                    if car is not None:
                        jax.block_until_ready(car)

    def fused_finish(self, pending):
        """Stage 4's data half: widen + unpack every received carrier
        into the halos (compiled updates, zero collectives) and
        reassemble the exchanged state; the caller's boundary compute
        reads the result."""
        leaves = list(pending["leaves"])
        order = [self._coords[d.id] for d in self.mesh.devices.flat]
        for dtype, idxs, shards, recv in pending["groups"]:
            nq = len(idxs)
            wire = wire_narrow_dtype(dtype, self.ex.wire_dtype)
            new_shards: Dict[Tuple[int, int, int], tuple] = {}
            for coords in recv:
                sizes = self._block_sizes(coords)
                args = tuple(s[coords] for s in shards)
                carriers = tuple(recv[coords])
                key = ("fupd", sizes, args[0].shape, str(dtype), nq,
                       str(wire))
                fn = self._jit(key, lambda: self._fused_update_fn(
                    sizes, args[0].shape, dtype, nq, wire))
                self._remember(key, args + carriers)
                new_shards[coords] = fn(*args, *carriers)
            for q, li in enumerate(idxs):
                leaves[li] = jax.make_array_from_single_device_arrays(
                    leaves[li].shape, pending["sharding"],
                    [new_shards[c][q] for c in order],
                )
        return jax.tree.unflatten(pending["treedef"], leaves)

    def _exchange_once(self, state):
        """One standalone fused exchange (no compute slotted in): the
        same pack → start → wait → update schedule, back to back."""
        pending = self.fused_start(state)
        self.fused_wait(pending)
        return self.fused_finish(pending)


def run_fused_substep(emu, state, interior, boundary, rec=None, dcn=None):
    """One host-orchestrated fused substep — THE shared overlap
    protocol of the fused step loops (ops/jacobi._compile_jacobi_fused,
    astaroth/integrate.make_fused_astaroth_loop): start every emulated
    copy, dispatch the caller's interior compute while they fly, wait,
    unpack, then the caller's boundary compute, each stage under its
    variant-tagged ``fused.*`` span so every fused loop reports the same
    overlap semantics.

    ``interior()`` returns the interior-computed output; ``boundary
    (exchanged_state, out)`` returns the finished output. Both must be
    collective-free compiled programs. Returns ``(exchanged_state, out,
    interior_seconds, total_seconds)`` — the caller accumulates the two
    times into its ``fused.overlap_fraction`` gauge.

    ``dcn`` is the hierarchical fix-up (the sequential DCN schedule of
    parallel/hierarchy.py): applied to the exchanged state AFTER
    ``fused_finish`` — the fused messages are exact-extent, so the
    cross-host slabs must be extracted post-inner, when sender
    orthogonal halos are valid — and BEFORE the boundary compute reads
    the host-boundary halos."""
    import time as _time

    from ..obs import telemetry

    rec = rec or telemetry.get()
    t0 = _time.perf_counter()
    with rec.span("fused.pack", phase="exchange", variant="fused"):
        pending = emu.fused_start(state)
    t1 = _time.perf_counter()
    with rec.span("fused.interior", phase="compute", variant="fused"):
        out = interior()
        jax.block_until_ready(out)
    t2 = _time.perf_counter()
    with rec.span("fused.dma_wait", phase="exchange", variant="fused"):
        emu.fused_wait(pending)
    cur2 = emu.fused_finish(pending)
    if dcn is not None:
        with rec.span("fused.dcn", phase="exchange", variant="fused"):
            cur2 = dcn(cur2)
    with rec.span("fused.boundary", phase="compute", variant="fused"):
        out = boundary(cur2, out)
        jax.block_until_ready(out)
    t3 = _time.perf_counter()
    return cur2, out, t2 - t1, t3 - t0
