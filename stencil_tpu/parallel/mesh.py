"""Device-mesh construction for 3D grid decomposition.

The TPU-native replacement for the reference's rank/GPU assignment and
topology discovery (reference: src/stencil.cu:9-137, mpi_topology.hpp,
gpu_topology.cpp): instead of probing NVML link distances and enabling CUDA
peer access, we lay the partition grid onto a ``jax.sharding.Mesh`` whose
axis ordering determines which grid neighbors are ICI-adjacent.
``mesh_utils.create_device_mesh`` performs the physical-topology-aware
assignment that the reference's ``NodeAware`` QAP placement computes
numerically (placement refinements live in ``placement.py``).

Mesh axis names are ``('z', 'y', 'x')`` in that order, matching the stacked
block array layout ``(bz, by, bx, pz, py, px)``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..geometry import Dim3

AXIS_X = "x"
AXIS_Y = "y"
AXIS_Z = "z"
# Mesh/array-major order: z slowest, x fastest.
MESH_AXES = (AXIS_Z, AXIS_Y, AXIS_X)

# The one PartitionSpec of the stacked-block layout (bz, by, bx, pz, py, px):
# block-grid dims sharded over the mesh, data dims replicated. It lives here
# (not in exchange.py) because it is a fact of the mesh-axis naming, shared
# by the manual shard_map exchanges AND the AUTO_SPMD jit programs whose
# collectives the SPMD partitioner synthesizes from this sharding.
BLOCK_PSPEC = P(AXIS_Z, AXIS_Y, AXIS_X, None, None, None)


def block_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding of the stacked-block layout over a grid mesh — the
    in/out sharding every exchange strategy and jitted step pins."""
    return NamedSharding(mesh, BLOCK_PSPEC)


def grid_mesh(dim, devices: Optional[Sequence] = None, ordered: bool = False) -> Mesh:
    """Build a ``(dz, dy, dx)`` mesh for a partition grid ``dim`` (x, y, z).

    ``devices=None`` uses all local devices; on a real multi-chip TPU slice
    the layout goes through ``mesh_utils.create_device_mesh`` (ICI-aware —
    the built-in NodeAware analogue). ``ordered=True`` keeps the caller's
    exact device order (used when a Placement strategy has already arranged
    them; the Trivial-placement analogue, partition.hpp:291).
    """
    d = Dim3.of(dim)
    shape = (d.z, d.y, d.x)
    if devices is None:
        import jax

        devices = jax.devices()
    devices = list(devices)
    n = int(np.prod(shape))
    if len(devices) != n:
        raise ValueError(f"partition {d} needs {n} devices, have {len(devices)}")
    if (
        not ordered
        and n > 1
        and len({dev.platform for dev in devices}) == 1
        and devices[0].platform == "tpu"
    ):
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(shape, devices=devices)
    else:
        arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, MESH_AXES)


def mesh_dim(mesh: Mesh) -> Dim3:
    """Partition grid extent (x, y, z) of a grid mesh."""
    return Dim3(
        mesh.shape[AXIS_X],
        mesh.shape[AXIS_Y],
        mesh.shape[AXIS_Z],
    )
