"""Logical neighbor topology over the subdomain grid + link-cost discovery.

TPU-native analogue of the reference ``Topology``
(reference: include/stencil/topology.hpp:9-30, src/topology.cpp) — periodic
boundaries only, like the reference (non-periodic is fatal there).

:func:`link_cost_matrix` is the physical half the placement leg consumes
(plan/cost.py's topology-aware PlanChoice dimension): the per-device-pair
distance matrix the QAP prices wire volume against — ICI torus hop
distance where device coords exist (TPU slices), the process-boundary
penalty ladder elsewhere (the reference's NVML ancestor-ladder distances,
src/gpu_topology.cpp:22-95, re-read from the JAX device objects)."""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Sequence

from ..geometry import Dim3
from .device_topo import (distance_matrix, host_assignment,  # noqa: F401
                          host_groups, virtual_hosts)


class Boundary(enum.Enum):
    NONE = 0
    PERIODIC = 1


@dataclass(frozen=True)
class Neighbor:
    index: Dim3
    exists: bool


class Topology:
    def __init__(self, extent, boundary: Boundary = Boundary.PERIODIC):
        if boundary != Boundary.PERIODIC:
            raise ValueError("only periodic boundaries are supported (as in the reference)")
        self.extent = Dim3.of(extent)
        self.boundary = boundary

    def get_neighbor(self, index, direction) -> Neighbor:
        idx = Dim3.of(index)
        d = Dim3.of(direction)
        if not (abs(d.x) <= 1 and abs(d.y) <= 1 and abs(d.z) <= 1):
            raise ValueError(f"direction components must be in "
                             f"{{-1, 0, 1}}; got {d}")
        return Neighbor(index=(idx + d).wrap(self.extent), exists=True)


def link_cost_matrix(devices: Sequence):
    """Per-device-pair link cost (lower = faster) for the placement QAP.

    Delegates to :func:`~.device_topo.distance_matrix`: ICI torus hop
    count between chips that expose ``coords`` (every extra hop costs
    proportionally more wire time — the manhattan model, exact for
    non-wrapped observable meshes), and the locality ladder for devices
    without coords — same process 1.0, cross-process 7.0 (the reference's
    remote-rank penalty). A single-process CPU mesh is therefore UNIFORM
    off-diagonal, which the plan search recognizes
    (``plan.cost.uniform_link_costs``) and prices every placement
    identically — identity wins, by design: placement only pays off where
    the fabric is actually non-uniform. ``STENCIL_VIRTUAL_HOSTS=N``
    (see :func:`~.device_topo.host_assignment`) makes the single-process
    mesh non-uniform on purpose: crossing links between the N emulated
    hosts take the 7.0 process-boundary cost, giving the two-level QAP
    and the hierarchical plan search a real ladder to price in-process."""
    return distance_matrix(devices)
