"""Logical neighbor topology over the subdomain grid.

TPU-native analogue of the reference ``Topology``
(reference: include/stencil/topology.hpp:9-30, src/topology.cpp) — periodic
boundaries only, like the reference (non-periodic is fatal there)."""

from __future__ import annotations

import enum
from dataclasses import dataclass

from ..geometry import Dim3


class Boundary(enum.Enum):
    NONE = 0
    PERIODIC = 1


@dataclass(frozen=True)
class Neighbor:
    index: Dim3
    exists: bool


class Topology:
    def __init__(self, extent, boundary: Boundary = Boundary.PERIODIC):
        if boundary != Boundary.PERIODIC:
            raise ValueError("only periodic boundaries are supported (as in the reference)")
        self.extent = Dim3.of(extent)
        self.boundary = boundary

    def get_neighbor(self, index, direction) -> Neighbor:
        idx = Dim3.of(index)
        d = Dim3.of(direction)
        if not (abs(d.x) <= 1 and abs(d.y) <= 1 and abs(d.z) <= 1):
            raise ValueError(f"direction components must be in "
                             f"{{-1, 0, 1}}; got {d}")
        return Neighbor(index=(idx + d).wrap(self.extent), exists=True)
