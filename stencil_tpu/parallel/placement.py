"""Placement strategies: which device hosts which subdomain.

TPU-native re-design of the reference's Placement hierarchy
(reference: include/stencil/partition.hpp:264-289 abstract, :291-445
Trivial, :525-831 NodeAware QAP placement;
src/placement_intranoderandom.cpp IntraNodeRandom ablation baseline).

A placement's job here is to ORDER the device list before the 3D grid mesh
is built: grid position (ix, iy, iz) takes the device at row-major (z, y, x)
index ``iz*dy*dx + iy*dx + ix`` of the arranged list. On real TPU slices
``mesh_utils.create_device_mesh`` already produces an ICI-aware layout;
NodeAware reproduces the reference's *numeric* approach (QAP over a
comm-volume matrix and a 1/bandwidth distance matrix) and is useful when
the automatic layout is unavailable (explicit device lists, CPU meshes) and
as the placement-ablation axis of the benchmarks (--naive / --random flags,
bin/exchange_weak.cu:74,149-153).
"""

from __future__ import annotations

import random
from typing import List, Sequence

import numpy as np

from ..geometry import Dim3, halo_extent
from ..utils import logging as log
from . import qap
from .device_topo import distance_matrix


class Placement:
    """Orders devices for mesh construction (lowest index = block (0,0,0))."""

    def arrange(self, devices: Sequence, spec) -> List:
        raise NotImplementedError


class Trivial(Placement):
    """Devices in given order — the reference's rank-order round-robin
    (partition.hpp:291-445)."""

    def arrange(self, devices: Sequence, spec) -> List:
        return list(devices)


class IntraNodeRandom(Placement):
    """Deterministic random shuffle within each host's devices — the
    placement-ablation baseline (reference:
    src/placement_intranoderandom.cpp, seeded mt19937(0) shuffle)."""

    def __init__(self, seed: int = 0):
        self.seed = seed

    def arrange(self, devices: Sequence, spec) -> List:
        rng = random.Random(self.seed)
        by_host: dict = {}
        order: List = []
        for d in devices:
            by_host.setdefault(d.process_index, []).append(d)
        for host in sorted(by_host):
            group = by_host[host]
            rng.shuffle(group)
            order.extend(group)
        return order


def comm_matrix(spec) -> np.ndarray:
    """Pairwise halo-volume matrix between grid positions, periodic wrap
    (reference: partition.hpp:722-752; cost = halo_extent(dir).flatten(),
    :535-540)."""
    dim = spec.dim
    n = dim.flatten()
    m = np.zeros((n, n), dtype=np.float64)

    def lin(idx: Dim3) -> int:
        return idx.x + idx.y * dim.x + idx.z * dim.x * dim.y

    for iz in range(dim.z):
        for iy in range(dim.y):
            for ix in range(dim.x):
                src = Dim3(ix, iy, iz)
                sz = spec.block_size(src)
                for dz in (-1, 0, 1):
                    for dy in (-1, 0, 1):
                        for dx in (-1, 0, 1):
                            d = Dim3(dx, dy, dz)
                            if d == Dim3(0, 0, 0):
                                continue
                            if spec.radius.dir(d) == 0:
                                continue
                            dst = (src + d).wrap(dim)
                            if dst == src:
                                continue  # self-wrap: no inter-device traffic
                            m[lin(src), lin(dst)] += halo_extent(
                                d, sz, spec.radius
                            ).flatten()
    return m


class FixedAssignment(Placement):
    """An explicit, pre-solved block→device assignment — the strategy
    form of a ``PlanChoice.placement`` tuple: grid position i (row-major
    z, y, x) is hosted by ``devices[assignment[i]]``. What the plan
    probes and the placed bench legs arrange with (the tuned assignment
    must realize EXACTLY, not be re-solved)."""

    def __init__(self, assignment):
        self.assignment = tuple(int(v) for v in assignment)
        if sorted(self.assignment) != list(range(len(self.assignment))):
            raise ValueError(
                f"assignment {self.assignment} is not a permutation of "
                f"range({len(self.assignment)})")

    def arrange(self, devices: Sequence, spec) -> List:
        if len(devices) != len(self.assignment):
            raise ValueError(
                f"assignment covers {len(self.assignment)} devices; "
                f"got {len(devices)}")
        return [devices[self.assignment[i]]
                for i in range(len(self.assignment))]


class NodeAware(Placement):
    """QAP-matched placement: assign subdomains to devices so that heavy
    halo traffic rides the fastest links (reference: partition.hpp:525-831,
    rank 0 solves and broadcasts; here every process computes the same
    deterministic answer)."""

    def __init__(self, timeout_s: float = 10.0, exact_limit: int = 8):
        self.timeout_s = timeout_s
        self.exact_limit = exact_limit

    def arrange(self, devices: Sequence, spec) -> List:
        n = len(devices)
        w = comm_matrix(spec)
        dist = distance_matrix(devices)
        if n <= self.exact_limit:
            f, cost = qap.solve(w, dist, timeout_s=self.timeout_s)
        else:
            f, cost = qap.solve_catch(w, dist)
        log.debug(f"NodeAware placement cost {cost}: {f}")
        # f[i] = device slot for grid position i (row-major z,y,x)
        return [devices[f[i]] for i in range(n)]
