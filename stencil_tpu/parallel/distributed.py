"""Multi-process initialization and colocation discovery.

The TPU-native analogue of the reference's MPI bootstrap: where the
reference discovers colocated ranks with ``MPI_Comm_split_type(SHARED)``
(reference: mpi_topology.hpp:20-30) and launches via mpiexec/jsrun
(reference: README.md:131-168, scripts/summit/*.sh), a JAX multi-host run
calls :func:`init_distributed` in every process before any device access.
After it returns, ``jax.devices()`` is the *global* device list and the
whole stack — NodePartition's host-level outer split (api.realize),
process-grouped placement (placement.IntraNodeRandom), cross-process
``ppermute``s in the exchange — operates over all hosts; XLA routes the
collectives over ICI within a slice and DCN/Gloo across hosts.

Launch styles:
- TPU pods / GKE: ``init_distributed()`` with no arguments — JAX picks up
  the cluster environment automatically.
- Manual / CPU simulation (the reference's "2 ranks on one node" idiom,
  test/CMakeLists.txt:49): pass ``coordinator``/``num_processes``/
  ``process_id`` explicitly or via ``STENCIL_COORDINATOR``,
  ``STENCIL_NUM_PROCESSES``, ``STENCIL_PROCESS_ID`` env vars;
  ``local_cpu_devices=N`` gives each process N virtual CPU devices
  (collectives ride Gloo). Exercised by tests/test_multiprocess.py.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence


def init_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_cpu_devices: int = 0,
):
    """Initialize JAX's distributed runtime (call before any device use).

    Returns ``(process_index, process_count)``. All arguments fall back to
    the ``STENCIL_COORDINATOR`` / ``STENCIL_NUM_PROCESSES`` /
    ``STENCIL_PROCESS_ID`` environment variables; with none set, JAX's
    automatic cluster detection is used (TPU pod slices).
    """
    import jax

    coordinator = coordinator or os.environ.get("STENCIL_COORDINATOR")
    if num_processes is None and os.environ.get("STENCIL_NUM_PROCESSES"):
        num_processes = int(os.environ["STENCIL_NUM_PROCESSES"])
    if process_id is None and os.environ.get("STENCIL_PROCESS_ID"):
        process_id = int(os.environ["STENCIL_PROCESS_ID"])

    if local_cpu_devices:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", int(local_cpu_devices))

    if coordinator is None:
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return jax.process_index(), jax.process_count()


def maybe_init_from_env() -> bool:
    """Initialize the distributed runtime iff the STENCIL_* launch env is
    present (set by scripts/launch_multiprocess.sh or a cluster launcher);
    no-op otherwise. Returns whether initialization happened. Apps call
    this at the top of ``main()`` so the same CLI works single- and
    multi-process."""
    if not os.environ.get("STENCIL_COORDINATOR"):
        return False
    init_distributed(
        local_cpu_devices=int(os.environ.get("STENCIL_LOCAL_CPU_DEVICES", "0"))
    )
    return True


def colocated_devices(devices: Optional[Sequence] = None) -> Dict[int, List]:
    """Devices grouped by owning process — the ``MpiTopology.colocated``
    analogue (reference: mpi_topology.hpp:95)."""
    import jax

    devices = list(devices) if devices is not None else jax.devices()
    groups: Dict[int, List] = {}
    for d in devices:
        groups.setdefault(d.process_index, []).append(d)
    return groups


def local_devices(devices: Optional[Sequence] = None) -> List:
    """This process's own devices (the reference's per-rank GPU set,
    src/stencil.cu:74-85)."""
    import jax

    return colocated_devices(devices).get(jax.process_index(), [])
