#!/usr/bin/env python
"""Headline benchmark: jacobi3d Mcells/s/chip at 512^3 (reference default
size, bin/jacobi3d.cu:100-102) plus halo-exchange GB/s, printed as ONE JSON
line. Runs on whatever accelerator JAX finds (the driver provides one TPU
chip); falls back to a small CPU run if only CPU is available.

vs_baseline compares against this repo's recorded round-1 TPU numbers in
BASELINE.md (the reference publishes no absolute numbers — BASELINE.md §1).
"""

from __future__ import annotations

import json
import time

# Round-1 recorded TPU v5e-chip numbers (see BASELINE.md "Recorded numbers").
BASELINE_MCELLS_PER_S_PER_CHIP = 3394.8
BASELINE_EXCHANGE_GB_S = 2.18


def main() -> int:
    import os
    import sys

    import jax

    # wall-clock guard: the driver must ALWAYS get the one JSON line, even
    # when the tunneled platform is slow — optional detail legs are skipped
    # once the budget is spent (headline jacobi always runs)
    budget_s = float(os.environ.get("STENCIL_BENCH_BUDGET_S", "900"))
    bench_t0 = time.time()

    def leg(name):
        left = budget_s - (time.time() - bench_t0)
        print(f"[bench] {name}: {time.time()-bench_t0:.0f}s elapsed, "
              f"{left:.0f}s budget left", file=sys.stderr, flush=True)
        return left > 0

    on_accel = jax.devices()[0].platform != "cpu"
    n = 512 if on_accel else 128
    # the tunneled platform costs ~87 ms fixed per dispatch; large fused
    # chunks amortize it (the reference's >=30-iteration timing loops,
    # bin/exchange_weak.cu:168-177, served the same purpose for CUDA
    # launch/MPI overhead)
    # 360 amortizes the ~87 ms fixed dispatch cost to ~0.24 ms per iteration
    chunk = 360 if on_accel else 3

    from stencil_tpu.apps.jacobi3d import run
    from stencil_tpu.utils.statistics import Statistics
    from stencil_tpu.utils.sync import hard_sync

    leg("jacobi3d headline")
    r = run(n, n, n, iters=3 * chunk, weak=False, devices=jax.devices()[:1],
            warmup=1, chunk=chunk)
    mcells = r["mcells_per_s_per_dev"]

    # exchange benchmark: radius-3, 4 float quantities (exchange_weak config,
    # bin/exchange_weak.cu:49-51,143), fused loop of `chunk` exchanges
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks
    import numpy as np

    ex_gb_s = 0.0
    if leg("halo exchange"):
        spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3))
        mesh = grid_mesh(spec.dim, jax.devices()[:1])
        ex = HaloExchange(spec, mesh)
        loop = ex.make_loop(chunk)
        state = {
            i: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
            for i in range(4)
        }
        state = loop(state)  # compile + warm
        hard_sync(state)
        st = Statistics()
        for _ in range(3):
            t0 = time.perf_counter()
            state = loop(state)
            hard_sync(state)
            st.insert((time.perf_counter() - t0) / chunk)
        ex_gb_s = ex.bytes_logical([4] * 4) / st.trimean() / 1e9
        del state

    # astaroth flagship detail (BASELINE config 4 family): 256^3, 8 fp32
    # fields, fused Pallas RK3 substeps; skipped off-accelerator, via
    # STENCIL_BENCH_FAST=1, or when over budget (the three sliding-window
    # substep kernels compile in ~50 s each)
    asta_ms = None
    if (on_accel and not os.environ.get("STENCIL_BENCH_FAST")
            and leg("astaroth 256^3")):
        from stencil_tpu.apps.astaroth import run as asta_run

        # chunk 30 amortizes the ~87 ms fixed dispatch cost to <3 ms/iter
        a = asta_run(
            iters=60, devices=jax.devices()[:1], dtype="float32", nx=256, chunk=30
        )
        asta_ms = round(a["iter_trimean_s"] * 1e3, 2)
    leg("done")

    value = round(mcells, 1)
    # the recorded baseline is a 512^3 TPU number; a CPU fallback run gets its
    # own metric name and no baseline ratio so the two are never conflated
    comparable = on_accel and n == 512
    vs = value / BASELINE_MCELLS_PER_S_PER_CHIP if comparable else 0.0
    metric = (
        "jacobi3d_512_mcells_per_s_per_chip"
        if comparable
        else f"jacobi3d_{n}_mcells_per_s_per_chip_cpu_fallback"
    )
    print(
        json.dumps(
            {
                "metric": metric,
                "value": value,
                "unit": "Mcells/s",
                "vs_baseline": round(vs, 3),
                "detail": {
                    "iter_trimean_s": round(r["iter_trimean_s"], 6),
                    "exchange_gb_per_s_r3_4q": round(ex_gb_s, 2),
                    "exchange_vs_baseline": (
                        round(ex_gb_s / BASELINE_EXCHANGE_GB_S, 3) if comparable else 0.0
                    ),
                    "astaroth_256_iter_ms": asta_ms,
                    "platform": jax.devices()[0].platform,
                    "size": n,
                },
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
