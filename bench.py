#!/usr/bin/env python
"""Headline benchmark: jacobi3d Mcells/s/chip at 512^3 (reference default
size, bin/jacobi3d.cu:100-102) plus halo-exchange GB/s and the astaroth
flagship details, printed as ONE JSON line with rc=0 — always.

Architecture (round-4 hardening, refactored onto the obs/ watchdog): the
PARENT process never initializes a JAX backend — it does not even import
the ``stencil_tpu`` package (whose ``__init__`` imports jax); the revival
watcher, ``stencil_tpu/obs/watchdog.py``, is pure stdlib and loaded by
FILE PATH. The tunneled TPU plugin can stall ``jax.devices()``
indefinitely or die mid-``device_put`` (round-3 BENCH artifact, rc=1), so
all measurement runs in CHILD subprocesses supervised on two layered
deadlines (total budget + telemetry heartbeat staleness — a wedged child
is killed as a STALL long before the budget):

  1. accelerator child (whatever backend JAX finds — the driver's TPU chip),
     retried once with backoff;
  2. forced-CPU child (``jax.config.update('jax_platforms','cpu')`` before
     backend init — the env-var spelling is ignored once the tunnel plugin
     registers) with small sizes;
  3. a last-resort static JSON line if even the CPU child fails.

Children emit heartbeats through stencil_tpu.obs.telemetry (a background
beat thread plus per-leg beats); set STENCIL_BENCH_LOG_DIR to archive
per-attempt child logs, STENCIL_BENCH_HEARTBEAT_S to tune the stall
deadline, and STENCIL_BENCH_METRICS_OUT to also get the children's
metrics JSONL (same schema as the apps' --metrics-out).

vs_baseline for the headline compares against this repo's recorded ROUND-1
TPU number (the reference publishes no absolute numbers — BASELINE.md §1),
so the driver sees the cumulative speedup (~23x as of round 3). The
exchange ratio compares like-for-like against the ROUND-2 Pallas self-fill
number measured with this exact leg (round 1's 2.18 GB/s was the
pre-Pallas slab path; dividing by it conflated a kernel rewrite with a
methodology change — VERDICT r3 weak #6).
"""

from __future__ import annotations

import json
import os
import sys
import time

# Recorded TPU v5e single-chip numbers (BASELINE.md "Recorded numbers").
BASELINE_MCELLS_PER_S_PER_CHIP = 3394.8  # round 1, jacobi3d 512^3
BASELINE_EXCHANGE_GB_S = 15.75  # round 2, Pallas self-fill, same leg as below

# The one JSON line the driver reads is marked so the parent can find it in
# the child's stdout regardless of logging noise around it.
SENTINEL = "STENCIL_BENCH_JSON: "


# ---------------------------------------------------------------- child side


def _child_main(mode: str, resume: bool = False) -> int:
    """Measure and print SENTINEL+JSON. ``mode``: 'accel' | 'cpu'.

    ``resume`` is what the parent's Revival ladder passes on every rung
    after the first: with STENCIL_BENCH_CKPT_DIR set, the jacobi headline
    leg checkpoints per chunk and a revived child continues from its last
    durable step instead of step 0 (a CPU fallback whose domain differs
    simply finds no compatible snapshot and starts fresh — the elastic
    restore degrades, never crashes)."""
    hang = float(os.environ.get("STENCIL_BENCH_SELFTEST_HANG_S", "0") or 0)
    if hang and mode == "accel":
        # self-test hook (tests/test_driver_hardening.py): simulate the
        # wedged-tunnel backend init the parent must be able to time out
        time.sleep(hang)

    import jax

    if mode == "cpu":
        # must go through the config API before backend init: the tunnel's
        # sitecustomize pins JAX_PLATFORMS and the plugin ignores the env var
        jax.config.update("jax_platforms", "cpu")

    # telemetry: heartbeats for the supervising watchdog (no-op unsupervised)
    # + optional metrics JSONL; configure BEFORE any backend init so a
    # wedged init is already covered by the beat thread
    from stencil_tpu.obs import telemetry

    rec = telemetry.configure(
        metrics_out=os.environ.get("STENCIL_BENCH_METRICS_OUT") or None,
        app="bench",
    )

    if mode == "cpu":
        # 8 virtual devices (after the stencil_tpu import applied the jax
        # compat shims) so the batched-exchange leg runs on a real 2x2x2
        # CPU mesh; the other legs pin devices[:1] and are unaffected
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except Exception:
            pass

    budget_s = float(os.environ.get("STENCIL_BENCH_LEG_BUDGET_S", "840"))
    t0 = time.time()
    errors: dict[str, str] = {}

    def leg(name: str) -> bool:
        left = budget_s - (time.time() - t0)
        rec.heartbeat()
        print(
            f"[bench:{mode}] {name}: {time.time()-t0:.0f}s elapsed, "
            f"{left:.0f}s budget left",
            file=sys.stderr,
            flush=True,
        )
        return left > 0

    on_accel = jax.devices()[0].platform != "cpu"
    n = 512 if on_accel else 128
    # the tunneled platform costs ~87 ms fixed per dispatch; large fused
    # chunks amortize it (the reference's >=30-iteration timing loops,
    # bin/exchange_weak.cu:168-177, served the same purpose for CUDA
    # launch/MPI overhead). 360 amortizes to ~0.24 ms per iteration.
    chunk = 360 if on_accel else 3

    from stencil_tpu.apps.jacobi3d import run
    from stencil_tpu.fault import FAULT_RC, RecoveryExhausted
    from stencil_tpu.utils.statistics import Statistics
    from stencil_tpu.utils.sync import hard_sync

    # headline jacobi: REQUIRED — if this dies the child fails and the
    # parent falls back. With a checkpoint dir, the leg is durable per
    # chunk and a revived child (--resume) continues mid-campaign. The
    # health guard checks the field once per fused chunk: an in-band NaN
    # burst (a bad device, a corrupted payload) rolls back to the last
    # durable chunk instead of poisoning the headline number, and a run
    # that cannot recover exits the DISTINCT fault rc so the parent's
    # ladder reports "numerics broken", not a generic crash.
    ckpt_dir = os.environ.get("STENCIL_BENCH_CKPT_DIR") or None
    if ckpt_dir:
        # per-config subdir: the 128^3 CPU fallback must never repoint
        # LATEST or prune away the 512^3 accel campaign's snapshots
        ckpt_dir = os.path.join(ckpt_dir, f"jacobi{n}")
    leg("jacobi3d headline")
    try:
        r = run(n, n, n, iters=3 * chunk, weak=False,
                devices=jax.devices()[:1],
                warmup=1, chunk=chunk,
                ckpt_dir=ckpt_dir, ckpt_every=chunk if ckpt_dir else 0,
                resume=resume and ckpt_dir is not None,
                health_every=chunk)
        import math

        if ckpt_dir and not math.isfinite(r["iter_trimean_s"]):
            # the previous child finished this leg (snapshot at step==iters)
            # but died before delivering the sentinel, so its timings are
            # gone: a resume has nothing to time and would report a 0.0
            # headline — re-measure fresh instead
            print(f"[bench:{mode}] resume found the jacobi leg complete; "
                  "re-measuring", file=sys.stderr, flush=True)
            r = run(n, n, n, iters=3 * chunk, weak=False,
                    devices=jax.devices()[:1], warmup=1, chunk=chunk,
                    ckpt_dir=ckpt_dir, ckpt_every=chunk, resume=False,
                    health_every=chunk)
    except RecoveryExhausted as e:
        print(f"[bench:{mode}] headline leg faulted beyond recovery: {e}",
              file=sys.stderr, flush=True)
        return FAULT_RC
    mcells = r["mcells_per_s_per_dev"]

    # exchange benchmark: radius-3, 4 float quantities (exchange_weak config,
    # bin/exchange_weak.cu:49-51,143), fused loop of `chunk` exchanges.
    # Timed twice: the manual AXIS_COMPOSED transport and the AUTO_SPMD
    # strategy whose collectives XLA's partitioner synthesizes — the
    # tracked manual-vs-auto leg of the bench_mpi_pack ablation
    # (reference: bin/bench_mpi_pack.cu:18-80; BASELINE.md "auto-SPMD").
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks
    import numpy as np

    def _exchange_leg(method, nq: int = 4, ndev: int = 1, nb: int = None,
                      batched: bool = True, dim: Dim3 = None,
                      placement=None, hierarchy=None) -> float:
        nb = nb if nb is not None else n
        if dim is None:
            dim = Dim3(2, 2, 2) if ndev == 8 else Dim3(1, 1, 1)
        spec = GridSpec(Dim3(nb, nb, nb), dim, Radius.constant(3))
        devs = jax.devices()[:ndev]
        if placement is not None:
            # topology-aware block placement: mesh position i hosted by
            # devs[placement[i]] (the PlanChoice.placement convention)
            devs = [devs[placement[i]] for i in range(len(devs))]
        mesh = grid_mesh(spec.dim, devs, ordered=placement is not None)
        ex = HaloExchange(spec, mesh, method, batch_quantities=batched,
                          hierarchy=hierarchy)
        loop = ex.make_loop(chunk)
        state = {
            i: shard_blocks(np.zeros((nb, nb, nb), np.float32), spec, mesh)
            for i in range(nq)
        }
        state = loop(state)  # compile + warm
        hard_sync(state)
        st = Statistics()
        for _ in range(3):
            t1 = time.perf_counter()
            state = loop(state)
            hard_sync(state)
            st.insert((time.perf_counter() - t1) / chunk)
        return ex.bytes_logical([4] * nq) / st.trimean() / 1e9

    ex_gb_s = 0.0
    if leg("halo exchange"):
        try:
            ex_gb_s = _exchange_leg(Method.AXIS_COMPOSED)
        except Exception as e:  # optional leg: record, keep going
            errors["exchange"] = f"{type(e).__name__}: {e}"[:400]
    ex_auto_gb_s = 0.0
    if leg("halo exchange (auto-spmd)"):
        try:
            ex_auto_gb_s = _exchange_leg(Method.AUTO_SPMD)
        except Exception as e:
            errors["exchange_auto"] = f"{type(e).__name__}: {e}"[:400]

    # kernel-initiated remote-DMA exchange (ISSUE 10 / ROADMAP #2): the
    # fourth transport vs the composed baseline at the same config, on an
    # 8-device mesh so phases actually cross the wire. On TPU this times
    # the Pallas carrier kernels (pltpu.make_async_remote_copy — the
    # tx_colocated analogue, 0 ppermutes); on the CPU child it times the
    # host-orchestrated emulation, which is a CORRECTNESS vehicle — the
    # ratio is expected < 1 there and only the TPU number is the claim.
    ex_rd_gb_s = 0.0
    ex_rd_base_gb_s = 0.0
    if leg("halo exchange (remote-dma)"):
        try:
            rd = dict(nq=4, ndev=8 if len(jax.devices()) >= 8 else 1,
                      nb=min(n, 128))
            ex_rd_gb_s = _exchange_leg(Method.REMOTE_DMA, **rd)
            ex_rd_base_gb_s = _exchange_leg(Method.AXIS_COMPOSED, **rd)
        except Exception as e:
            errors["exchange_remote_dma"] = f"{type(e).__name__}: {e}"[:400]

    # fused compute+exchange jacobi (ROADMAP #5): the fused REMOTE_DMA
    # step — interior compute overlapping the kernel-initiated copies —
    # vs the serialized remote-dma step (exchange dispatch then sweep)
    # at 128^3 on the 8-device mesh. CPU-emulation caveat, exactly like
    # exchange_remote_dma_over_composed above: on the CPU child both
    # legs run the host-orchestrated schedule, so the ratio there prices
    # host orchestration, not ICI overlap — only the TPU mega-kernel
    # number carries the ROADMAP-5 claim. Ledger ingest auto-appends
    # every numeric key below via STENCIL_BENCH_LEDGER.
    jac_fused_mc = 0.0
    jac_rd_mc = 0.0
    if leg("jacobi fused-over-remote-dma (128^3, 8-dev)"):
        try:
            import jax.numpy as jnp

            from stencil_tpu.ops.jacobi import (INIT_TEMP, make_jacobi_loop,
                                                sphere_sel)

            nbf = min(n, 128)
            ndevf = 8 if len(jax.devices()) >= 8 else 1
            dimf = Dim3(2, 2, 2) if ndevf == 8 else Dim3(1, 1, 1)
            specf = GridSpec(Dim3(nbf, nbf, nbf), dimf, Radius.constant(1))
            meshf = grid_mesh(specf.dim, jax.devices()[:ndevf])
            self_ = shard_blocks(sphere_sel((nbf, nbf, nbf)), specf, meshf)
            field0 = shard_blocks(
                np.full((nbf,) * 3, INIT_TEMP, np.float32), specf, meshf)

            def jac_leg(fused: bool) -> float:
                ex = HaloExchange(specf, meshf, Method.REMOTE_DMA,
                                  fused=fused)
                sub_iters = 3
                loop = make_jacobi_loop(ex, sub_iters)
                c = field0
                nx_ = jax.device_put(jnp.zeros_like(c), ex.sharding())
                c, nx_ = loop(c, nx_, self_)  # compile + warm
                hard_sync((c, nx_))
                st = Statistics()
                for _ in range(2):
                    t1 = time.perf_counter()
                    c, nx_ = loop(c, nx_, self_)
                    hard_sync((c, nx_))
                    st.insert((time.perf_counter() - t1) / sub_iters)
                return nbf ** 3 / st.trimean() / 1e6

            jac_fused_mc = jac_leg(True)
            jac_rd_mc = jac_leg(False)
        except Exception as e:
            errors["jacobi_fused"] = f"{type(e).__name__}: {e}"[:400]

    # persistent whole-chunk jacobi (ROADMAP #7): the communication-
    # avoiding temporal-fusion variant — ONE deep (radius*k) exchange +
    # ONE k-substep chunk program per chunk, 2 dispatches per chunk
    # instead of 2k — vs the per-step fused kernel at 32^3 and 64^3 on
    # the 8-device mesh. Same CPU-emulation caveat as the fused leg: on
    # the CPU child both legs are host-orchestrated, the ratio prices
    # host dispatch amortization (which IS the lever the variant pulls),
    # and only the TPU mega-kernel number (scripts/probe_persistent.py,
    # item-1 session) carries the launch-count hardware claim. Ledger
    # ingest auto-appends both sizes via STENCIL_BENCH_LEDGER.
    jac_pers = {}
    if leg("jacobi persistent-over-fused (32^3/64^3, 8-dev)"):
        try:
            import jax.numpy as jnp

            from stencil_tpu.ops.jacobi import (INIT_TEMP, make_jacobi_loop,
                                                sphere_sel)

            ndevp_ = 8 if len(jax.devices()) >= 8 else 1
            dimp_ = Dim3(2, 2, 2) if ndevp_ == 8 else Dim3(1, 1, 1)
            kp = 2

            def pers_leg(nb: int, persistent: bool) -> float:
                spec_ = GridSpec(Dim3(nb, nb, nb), dimp_,
                                 Radius.constant(kp if persistent else 1))
                mesh_ = grid_mesh(spec_.dim, jax.devices()[:ndevp_])
                ex = HaloExchange(spec_, mesh_, Method.REMOTE_DMA,
                                  persistent=persistent,
                                  fused=not persistent)
                sub_iters = 4
                loop = make_jacobi_loop(
                    ex, sub_iters,
                    temporal_k=kp if persistent else None)
                sel_ = shard_blocks(sphere_sel((nb, nb, nb)), spec_, mesh_)
                c = shard_blocks(
                    np.full((nb,) * 3, INIT_TEMP, np.float32), spec_, mesh_)
                nx_ = jax.device_put(jnp.zeros_like(c), ex.sharding())
                c, nx_ = loop(c, nx_, sel_)  # compile + warm
                hard_sync((c, nx_))
                st = Statistics()
                for _ in range(2):
                    t1 = time.perf_counter()
                    c, nx_ = loop(c, nx_, sel_)
                    hard_sync((c, nx_))
                    st.insert((time.perf_counter() - t1) / sub_iters)
                return nb ** 3 / st.trimean() / 1e6

            for nb_ in (32, 64):
                jac_pers[f"jacobi_persistent_mcells_per_s_{nb_}"] = round(
                    pers_leg(nb_, True), 2)
                jac_pers[f"jacobi_fused_base_mcells_per_s_{nb_}"] = round(
                    pers_leg(nb_, False), 2)
                base_ = jac_pers[f"jacobi_fused_base_mcells_per_s_{nb_}"]
                jac_pers[f"jacobi_persistent_over_fused_{nb_}"] = (
                    round(jac_pers[f"jacobi_persistent_mcells_per_s_{nb_}"]
                          / base_, 3) if base_ else 0.0)
        except Exception as e:
            errors["jacobi_persistent"] = f"{type(e).__name__}: {e}"[:400]

    # quantity-batching A/B at Q=8 (the astaroth field count): one packed
    # ppermute carrier per axis phase vs one collective per quantity. On an
    # 8-device mesh (the CPU child forces 8 virtual devices) the partition
    # is 2x2x2 and the permute count drops 48 -> 6; a single accel chip
    # self-wraps and the leg measures the batched fill path instead.
    # nb is capped: Q=8 at 512^3 would not fit the leg budget.
    ex_bq_gb_s = 0.0
    ex_pq_gb_s = 0.0
    if leg("halo exchange (batched Q=8 A/B)"):
        try:
            ab = dict(nq=8, ndev=8 if len(jax.devices()) >= 8 else 1,
                      nb=min(n, 256))
            ex_bq_gb_s = _exchange_leg(Method.AXIS_COMPOSED, batched=True, **ab)
            ex_pq_gb_s = _exchange_leg(Method.AXIS_COMPOSED, batched=False, **ab)
        except Exception as e:
            errors["exchange_batched"] = f"{type(e).__name__}: {e}"[:400]

    # topology-aware placement leg (ISSUE 15 / ROADMAP #6): the same
    # composed exchange on an ANISOTROPIC 1x2x4 partition of the 8-dev
    # mesh, identity device order vs a rotated block->device assignment
    # (the PlanChoice.placement mechanism the QAP feeds). Results are
    # bit-identical by construction; the tracked ratio is a parity/no-
    # regression pin on the placed mesh path — on the single-process CPU
    # mesh every link costs the same, so ~1.0 is the honest expectation
    # and only a TPU slice (non-uniform ICI hops) can show a win.
    ex_placed_gb_s = 0.0
    ex_ident_gb_s = 0.0
    if leg("halo exchange (placed vs identity)"):
        try:
            ndevp8 = 8 if len(jax.devices()) >= 8 else 1
            pl = dict(nq=4, ndev=ndevp8, nb=min(n, 128),
                      dim=Dim3(1, 2, 4) if ndevp8 == 8 else Dim3(1, 1, 1))
            rot = tuple((i + 1) % ndevp8 for i in range(ndevp8))
            ex_placed_gb_s = _exchange_leg(
                Method.AXIS_COMPOSED, placement=rot if ndevp8 > 1 else None,
                **pl)
            ex_ident_gb_s = _exchange_leg(Method.AXIS_COMPOSED, **pl)
        except Exception as e:
            errors["exchange_placed"] = f"{type(e).__name__}: {e}"[:400]

    # hierarchical ICI+DCN leg (ISSUE 17 / ROADMAP #3): the composed
    # exchange at 128^3 on the 8-dev mesh split into 2 virtual hosts x 4
    # devices (STENCIL_VIRTUAL_HOSTS emulation), z-outer hierarchy vs
    # the flat single-level plan on the same 1x2x4 partition. Results
    # are bit-identical by construction; on the CPU child the "DCN"
    # copies are host-orchestrated device_puts between in-process
    # devices, so the tracked ratio prices that orchestration overhead
    # (expected <= 1), not a real two-tier fabric — only a multi-host
    # TPU run (scripts/probe_dcn.py seeds its calibration) carries the
    # cross-host overlap claim.
    ex_hier_gb_s = 0.0
    ex_hier_flat_gb_s = 0.0
    if leg("halo exchange (hierarchical vs flat, 2 virtual hosts)"):
        vh_prev = os.environ.get("STENCIL_VIRTUAL_HOSTS")
        try:
            ndevh = 8 if len(jax.devices()) >= 8 else 1
            hx = dict(nq=4, ndev=ndevh, nb=min(n, 128),
                      dim=Dim3(1, 2, 4) if ndevh == 8 else Dim3(1, 1, 1))
            if ndevh == 8:
                os.environ["STENCIL_VIRTUAL_HOSTS"] = "2"
                ex_hier_gb_s = _exchange_leg(
                    Method.AXIS_COMPOSED, hierarchy=("z", 2), **hx)
            ex_hier_flat_gb_s = _exchange_leg(Method.AXIS_COMPOSED, **hx)
        except Exception as e:
            errors["exchange_hierarchical"] = f"{type(e).__name__}: {e}"[:400]
        finally:
            if vh_prev is None:
                os.environ.pop("STENCIL_VIRTUAL_HOSTS", None)
            else:
                os.environ["STENCIL_VIRTUAL_HOSTS"] = vh_prev

    # exchange-plan autotuner leg (ROADMAP #3): tune (partition x method x
    # batching) for a radius-3 4-quantity config, then time the tuned plan
    # against the plan-less default (NodePartition + AXIS_COMPOSED +
    # batching) at the SAME size — the tracked plan_autotuned_over_default
    # ratio (> 1 means the autotuner beat the default). The tuner runs
    # in-memory here (no DB): the leg measures tuning quality, not cache
    # behavior (scripts/ci_plan_gate.py pins the zero-probe replay).
    plan_tuned_gb_s = 0.0
    plan_default_gb_s = 0.0
    plan_label = None
    plan_fingerprint = None
    plan_calibration = None
    if leg("exchange plan autotune"):
        try:
            from stencil_tpu.plan.autotune import autotune, default_choice

            nbp = min(n, 128) if on_accel else 64
            ndevp = 8 if len(jax.devices()) >= 8 else 1
            res = autotune(
                Dim3(nbp, nbp, nbp), Radius.constant(3), ["float32"] * 4,
                devices=jax.devices()[:ndevp], top_n=2, probe_iters=3,
            )
            ch = res.choice
            plan_label = ch.label()
            # the plan identity the observatory joins on: which exact
            # PlanChoice produced this leg, priced by which calibration
            plan_fingerprint = ch.fingerprint()
            plan_calibration = res.calibration_provenance
            from stencil_tpu.parallel import Method as _M

            plan_tuned_gb_s = _exchange_leg(
                _M(ch.method), nq=4, ndev=ndevp, nb=nbp,
                batched=ch.batch_quantities, dim=Dim3.of(ch.partition),
            )
            dflt = default_choice(res.config)
            plan_default_gb_s = _exchange_leg(
                _M(dflt.method), nq=4, ndev=ndevp, nb=nbp,
                batched=dflt.batch_quantities, dim=Dim3.of(dflt.partition),
            )
        except Exception as e:
            errors["plan_autotune"] = f"{type(e).__name__}: {e}"[:400]

    # multi-tenant campaign A/B (ROADMAP #4): B=64 independent 32^3
    # tenants served as ONE batched compiled program (batch axis sharded
    # over the mesh, zero collectives, per-tenant self-wrap halos) vs the
    # same 64 tenants run sequentially through the standard single-domain
    # machinery on the same devices — the tracked
    # campaign_batched_over_sequential ratio (> 1: batching wins) with
    # p50/p99 per-tenant step latency for the tail story.
    camp_b = camp_s = 0.0
    camp_p50 = camp_p99 = None
    if leg("multi-tenant campaign (B=64 32^3 A/B)"):
        try:
            import tempfile as _tf

            from stencil_tpu.campaign import (CampaignDriver, TenantJob,
                                              run_sequential)

            ndevc = 8 if len(jax.devices()) >= 8 else 1
            camp_B, camp_n, camp_steps = 64, 32, 6
            jobs = [TenantJob(f"t{i}", (camp_n, camp_n, camp_n), camp_steps,
                              "float32", seed=i) for i in range(camp_B)]
            camp_dir = os.environ.get("STENCIL_BENCH_CKPT_DIR") or None
            if camp_dir:
                # per-config subdir isolation (the headline-leg rule): a
                # CPU-fallback campaign must never repoint or prune an
                # accel campaign's per-tenant snapshots
                camp_dir = os.path.join(camp_dir, f"campaign{camp_B}x{camp_n}")
            else:
                camp_dir = _tf.mkdtemp(prefix="bench-campaign-")
            seq = run_sequential(jobs, devices=jax.devices()[:ndevc],
                                 chunk=3)
            bat = CampaignDriver(jobs, camp_B, camp_dir,
                                 devices=jax.devices()[:ndevc],
                                 chunk=3).run()
            import math as _math

            camp_b = bat["aggregate_mcells_per_s"]
            camp_s = seq["aggregate_mcells_per_s"]
            camp_p50, camp_p99 = bat["p50_step_s"], bat["p99_step_s"]
            if not _math.isfinite(camp_p50):
                camp_p50 = None  # a latency-less run must stay strict JSON
            if camp_p99 is not None and not _math.isfinite(camp_p99):
                camp_p99 = None
        except Exception as e:
            errors["campaign"] = f"{type(e).__name__}: {e}"[:400]

    # always-on serving leg (ISSUE 19): 16 pre-dropped jobs through the
    # serve scheduler's B=8 continuous-batching slot — tracked as
    # offered-load throughput (serve_tenants_per_hour) and the per-step
    # p99 the admission controller prices deadlines from (serve_p99_ms)
    serve_tph = 0.0
    serve_p99_ms = None
    if leg("always-on serve (16 jobs, continuous batching)"):
        try:
            import math as _math
            import tempfile as _tf

            from stencil_tpu.serve import ServeScheduler

            sdir = _tf.mkdtemp(prefix="bench-serve-")
            incoming = os.path.join(sdir, "jobs", "incoming")
            os.makedirs(incoming, exist_ok=True)
            serve_n, serve_jobs = 16, 16
            for i in range(serve_jobs):
                doc = {
                    "job": f"b-{i:04d}", "size": serve_n, "steps": 4,
                    "dtype": "float32", "workload": "jacobi", "seed": i,
                    "tenant": f"tenant-{i % 4}", "priority": "normal",
                }
                tmp = os.path.join(incoming, f".tmp-{i}")
                with open(tmp, "w") as f:
                    json.dump(doc, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, os.path.join(incoming, f"{doc['job']}.json"))
            ndevs = 8 if len(jax.devices()) >= 8 else 1
            summ = ServeScheduler(
                sdir, 8, devices=jax.devices()[:ndevs], chunk=2,
                poll_s=0.05, max_idle_s=0.5).serve()
            if summ["retired"] != serve_jobs:
                raise RuntimeError(
                    f"serve leg retired {summ['retired']}/{serve_jobs}")
            serve_tph = summ["tenants_per_hour"]
            p99 = summ.get("p99_step_s")
            if p99 is not None and _math.isfinite(p99):
                serve_p99_ms = p99 * 1e3
        except Exception as e:
            errors["serve"] = f"{type(e).__name__}: {e}"[:400]

    # serve capacity engine A/B (ISSUE 20): the SAME seeded mixed-tenant
    # queue — 16 SHALLOW buckets (2 normal tenants each at a distinct
    # size 20..35, 16 steps) plus a 4-job high bucket — through the
    # PR 19 fixed-slot daemon (B=8, head-of-queue buckets) and through
    # the capacity engine (elastic width 2..16, scored cross-bucket
    # packing, stride fairness). Shallow buckets are exactly where a
    # fixed slot bleeds: every chunk boundary device_gets and zeros the
    # FULL 8-lane batch for 2 live tenants, while the engine sizes each
    # slot to its queue depth. Each config gets a WARM pass on a shared
    # CompileCache first, so the measured pass prices scheduling and
    # host transfer, not compilation. Tracked: serve_mixed_over_fixed
    # (the >= 1.3x acceptance floor) and the high-priority p99 split
    # (the engine must not buy throughput with the high class's
    # latency).
    serve_mixed_tph = serve_mixed_fixed_tph = 0.0
    serve_mixed_ratio = 0.0
    serve_mixed_hi_p99 = serve_mixed_fixed_hi_p99 = None
    if leg("serve capacity engine (mixed tenants A/B)"):
        try:
            import math as _math
            import tempfile as _tf

            from stencil_tpu.campaign.compile_cache import CompileCache
            from stencil_tpu.serve import ServeScheduler

            def _mixed_drop(sdir):
                incoming = os.path.join(sdir, "jobs", "incoming")
                os.makedirs(incoming, exist_ok=True)
                docs = [{"job": f"n-{b:02d}-{j}", "size": 20 + b,
                         "steps": 16, "dtype": "float32",
                         "workload": "jacobi", "seed": b * 7 + j,
                         "tenant": f"tenant-{b % 4}",
                         "priority": "normal"}
                        for b in range(16) for j in range(2)]
                docs += [{"job": f"h-{i:04d}", "size": 10, "steps": 8,
                          "dtype": "float32", "workload": "jacobi",
                          "seed": 100 + i, "tenant": "tenant-hi",
                          "priority": "high"} for i in range(4)]
                for doc in docs:
                    tmp = os.path.join(incoming, f".tmp-{doc['job']}")
                    with open(tmp, "w") as f:
                        json.dump(doc, f)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(
                        tmp, os.path.join(incoming, f"{doc['job']}.json"))
                return len(docs)

            def _mixed_serve(cache, **cfg):
                sdir = _tf.mkdtemp(prefix="bench-serve-mixed-")
                n_jobs = _mixed_drop(sdir)
                ndevs = 8 if len(jax.devices()) >= 8 else 1
                summ = ServeScheduler(
                    sdir, 8, devices=jax.devices()[:ndevs], chunk=2,
                    poll_s=0.02, max_idle_s=0.1, cache=cache,
                    **cfg).serve()
                if summ["retired"] != n_jobs:
                    raise RuntimeError(
                        f"mixed serve retired {summ['retired']}/{n_jobs}")
                return summ

            def _hi_p99(summ):
                v = (summ.get("p99_ms_by_priority") or {}).get("high")
                return v if v is not None and _math.isfinite(v) else None

            engine_cfg = dict(slot_min=2, slot_max=16, packing=True,
                              fairness=True)
            cache_fixed, cache_engine = CompileCache(), CompileCache()
            _mixed_serve(cache_fixed)                  # warm pass:
            _mixed_serve(cache_engine, **engine_cfg)   # compiles cached
            fixed = _mixed_serve(cache_fixed)
            eng = _mixed_serve(cache_engine, **engine_cfg)
            serve_mixed_fixed_tph = fixed["tenants_per_hour"]
            serve_mixed_tph = eng["tenants_per_hour"]
            if serve_mixed_fixed_tph > 0:
                serve_mixed_ratio = serve_mixed_tph / serve_mixed_fixed_tph
            serve_mixed_hi_p99 = _hi_p99(eng)
            serve_mixed_fixed_hi_p99 = _hi_p99(fixed)
        except Exception as e:
            errors["serve_mixed"] = f"{type(e).__name__}: {e}"[:400]

    # astaroth flagship details (BASELINE configs 4/4b): 8 fp32 fields,
    # fused Pallas RK3 substeps; skipped off-accelerator, via
    # STENCIL_BENCH_FAST=1, or when over budget (the three sliding-window
    # substep kernels compile in ~50 s each; the 512^3 set in ~150 s)
    asta_ms = None
    asta512_ms = None
    if on_accel and not os.environ.get("STENCIL_BENCH_FAST"):
        from stencil_tpu.apps.astaroth import run as asta_run

        if leg("astaroth 256^3"):
            try:
                # chunk 30 amortizes the ~87 ms dispatch cost to <3 ms/iter
                a = asta_run(iters=60, devices=jax.devices()[:1],
                             dtype="float32", nx=256, chunk=30)
                asta_ms = round(a["iter_trimean_s"] * 1e3, 2)
            except Exception as e:
                errors["astaroth_256"] = f"{type(e).__name__}: {e}"[:400]
        # the open flagship target (512^3 <= 180 ms/iter) is driver-tracked
        # from round 4 on (VERDICT r3 item 8); needs ~180 s compile+run
        if leg("astaroth 512^3") and budget_s - (time.time() - t0) > 200:
            try:
                a = asta_run(iters=12, devices=jax.devices()[:1],
                             dtype="float32", nx=512, chunk=6)
                asta512_ms = round(a["iter_trimean_s"] * 1e3, 2)
            except Exception as e:
                errors["astaroth_512"] = f"{type(e).__name__}: {e}"[:400]

    # flagship-size jacobi (config-5 per-chip regime): 768^3 is where the
    # full-plane multistep self-capped the temporal depth at k=4
    # (55.3 Gcells/s, VERDICT r5 weak #2); the row-tiled staging restores
    # k=12 there. Optional LAST leg (after the driver-tracked astaroth
    # rows) — skipped off-accelerator, under STENCIL_BENCH_FAST, or when
    # the remaining budget cannot cover its ~2 min compile+run.
    jac768 = None
    if on_accel and not os.environ.get("STENCIL_BENCH_FAST"):
        if leg("jacobi3d 768^3") and budget_s - (time.time() - t0) > 150:
            try:
                r768 = run(768, 768, 768, iters=60, weak=False,
                           devices=jax.devices()[:1], warmup=1, chunk=30)
                jac768 = round(r768["mcells_per_s_per_dev"], 1)
            except Exception as e:
                errors["jacobi_768"] = f"{type(e).__name__}: {e}"[:400]
    leg("done")

    value = round(mcells, 1)
    # the recorded baseline is a 512^3 TPU number; a CPU fallback run gets its
    # own metric name and no baseline ratio so the two are never conflated
    comparable = on_accel and n == 512
    vs = value / BASELINE_MCELLS_PER_S_PER_CHIP if comparable else 0.0
    metric = (
        "jacobi3d_512_mcells_per_s_per_chip"
        if comparable
        else f"jacobi3d_{n}_mcells_per_s_per_chip_cpu_fallback"
    )
    detail = {
        "iter_trimean_s": round(r["iter_trimean_s"], 6),
        "exchange_gb_per_s_r3_4q": round(ex_gb_s, 2),
        # like-for-like: same Pallas self-fill leg as the round-2 baseline
        "exchange_vs_baseline": (
            round(ex_gb_s / BASELINE_EXCHANGE_GB_S, 3) if comparable else 0.0
        ),
        # the bench_mpi_pack ablation leg: manual transport over the
        # XLA-synthesized AUTO_SPMD path, same size/radius/quantities
        # (> 1 means the hand-built exchange wins)
        "exchange_auto_gb_per_s": round(ex_auto_gb_s, 2),
        "exchange_manual_over_auto": (
            round(ex_gb_s / ex_auto_gb_s, 3) if ex_auto_gb_s else 0.0
        ),
        # kernel-initiated remote-DMA transport over the composed ppermute
        # baseline at the same 8-dev config (> 1 means bypassing the XLA
        # collective path won; expected < 1 on the CPU emulation — only
        # the TPU carrier-kernel number carries the §5.8 claim)
        "exchange_remote_dma_gb_per_s": round(ex_rd_gb_s, 2),
        "exchange_remote_dma_base_gb_per_s": round(ex_rd_base_gb_s, 2),
        "exchange_remote_dma_over_composed": (
            round(ex_rd_gb_s / ex_rd_base_gb_s, 3)
            if ex_rd_base_gb_s else 0.0
        ),
        # fused compute+exchange step over the serialized remote-dma
        # step, 128^3 / 8-dev (> 1 means hiding the wire behind interior
        # compute won; on the CPU child both legs are the
        # host-orchestrated emulation — the ratio there prices host
        # orchestration, and only the TPU mega-kernel number carries the
        # ROADMAP-5 overlap claim)
        "jacobi_fused_mcells_per_s": round(jac_fused_mc, 2),
        "jacobi_remote_dma_mcells_per_s": round(jac_rd_mc, 2),
        "jacobi_fused_over_remote_dma": (
            round(jac_fused_mc / jac_rd_mc, 3) if jac_rd_mc else 0.0
        ),
        # persistent whole-chunk variant over the per-step fused kernel
        # at 32^3 and 64^3 (> 1 means paying 2 dispatches per k-step
        # chunk beat 2 per step; the tracked jacobi_persistent_over_
        # fused_{32,64} legs — CPU A/B here, TPU in the item-1 session)
        **jac_pers,
        # quantity-batching leg (Q=8, the astaroth field count): batched
        # packed-carrier exchange over the per-quantity program
        # (> 1 means one-collective-per-phase wins)
        "exchange_batchedq_gb_per_s": round(ex_bq_gb_s, 2),
        "exchange_perq_gb_per_s": round(ex_pq_gb_s, 2),
        "exchange_batchedq_over_perq": (
            round(ex_bq_gb_s / ex_pq_gb_s, 3) if ex_pq_gb_s else 0.0
        ),
        # topology-aware placement leg: placed (rotated assignment) over
        # identity on the anisotropic 1x2x4 8-dev partition — a parity/
        # no-regression pin on CPU (uniform links -> ~1.0); the QAP win
        # claim needs non-uniform ICI and lives in the TPU session
        "exchange_placed_gb_per_s": round(ex_placed_gb_s, 2),
        "exchange_identity_gb_per_s": round(ex_ident_gb_s, 2),
        "exchange_placed_over_identity": (
            round(ex_placed_gb_s / ex_ident_gb_s, 3)
            if ex_ident_gb_s else 0.0
        ),
        # hierarchical ICI+DCN leg: two-level (2 virtual hosts x 4 dev)
        # exchange over the flat plan at the same 1x2x4 config — a
        # parity/no-regression pin on CPU (the emulated DCN copies are
        # in-process device_puts, so <= 1 is the honest expectation);
        # the cross-host overlap claim needs a real multi-host fabric
        "exchange_hierarchical_gb_per_s": round(ex_hier_gb_s, 2),
        "exchange_hier_flat_gb_per_s": round(ex_hier_flat_gb_s, 2),
        "exchange_hierarchical_over_flat": (
            round(ex_hier_gb_s / ex_hier_flat_gb_s, 3)
            if ex_hier_flat_gb_s else 0.0
        ),
        # exchange-plan autotuner leg: tuned plan's bandwidth over the
        # plan-less default at the same config (> 1: the tuner won)
        "plan_autotuned_gb_per_s": round(plan_tuned_gb_s, 2),
        "plan_default_gb_per_s": round(plan_default_gb_s, 2),
        "plan_autotuned_over_default": (
            round(plan_tuned_gb_s / plan_default_gb_s, 3)
            if plan_default_gb_s else 0.0
        ),
        "plan_choice": plan_label,
        "plan_fingerprint": plan_fingerprint,
        "plan_calibration": plan_calibration,
        # multi-tenant campaign leg: one batched program serving B=64
        # 32^3 tenants over the sequential baseline (> 1: batching wins),
        # with the per-tenant step-latency tail (utils/statistics
        # percentiles) the serving story is judged on
        "campaign_batched_mcells_per_s": round(camp_b, 2),
        "campaign_sequential_mcells_per_s": round(camp_s, 2),
        "campaign_batched_over_sequential": (
            round(camp_b / camp_s, 3) if camp_s else 0.0
        ),
        "campaign_p50_step_s": (
            round(camp_p50, 6) if camp_p50 is not None else None
        ),
        "campaign_p99_step_s": (
            round(camp_p99, 6) if camp_p99 is not None else None
        ),
        # serving leg: offered-load throughput through the daemon's
        # continuous-batching scheduler and the per-step p99 (ms) its
        # admission controller prices deadlines from
        "serve_tenants_per_hour": round(serve_tph, 1),
        "serve_p99_ms": (
            round(serve_p99_ms, 3) if serve_p99_ms is not None else None
        ),
        # capacity-engine A/B: engine vs fixed-slot tenants/hour on the
        # seeded mixed queue (>= 1.3 is the ISSUE 20 acceptance floor)
        # and the high class's p99 under each scheduler
        "serve_mixed_tenants_per_hour": round(serve_mixed_tph, 1),
        "serve_mixed_fixed_tenants_per_hour": round(serve_mixed_fixed_tph, 1),
        "serve_mixed_over_fixed": round(serve_mixed_ratio, 3),
        "serve_mixed_high_p99_ms": (
            round(serve_mixed_hi_p99, 3)
            if serve_mixed_hi_p99 is not None else None
        ),
        "serve_mixed_fixed_high_p99_ms": (
            round(serve_mixed_fixed_hi_p99, 3)
            if serve_mixed_fixed_hi_p99 is not None else None
        ),
        "astaroth_256_iter_ms": asta_ms,
        "astaroth_512_iter_ms": asta512_ms,
        "jacobi3d_768_mcells_per_s": jac768,
        "platform": jax.devices()[0].platform,
        "size": n,
    }
    if errors:
        detail["leg_errors"] = errors
    print(
        SENTINEL
        + json.dumps(
            {
                "metric": metric,
                "value": value,
                "unit": "Mcells/s",
                "vs_baseline": round(vs, 3),
                "detail": detail,
            }
        ),
        flush=True,
    )
    return 0


# --------------------------------------------------------------- parent side


def _load_obs(stem: str, modname: str):
    """Load a stencil_tpu/obs/ module by FILE PATH.

    The parent must never import the ``stencil_tpu`` package: its
    ``__init__`` imports jax, and the wedge being supervised lives in JAX
    backend/plugin machinery. watchdog.py and ledger.py are pure stdlib
    by contract."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "stencil_tpu", "obs", f"{stem}.py",
    )
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    # register BEFORE exec: dataclasses resolves string annotations through
    # sys.modules[cls.__module__]
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_watchdog():
    return _load_obs("watchdog", "stencil_watchdog")


def _append_ledger(payload: dict) -> None:
    """Append the round's payload to the performance ledger named by
    STENCIL_BENCH_LEDGER (no-op otherwise): the driver's one JSON line
    becomes durable, diffable history that ``perf_tool trend``/``gate``
    read across rounds. STENCIL_BENCH_LABEL names the round (default: a
    timestamp label). Best-effort by design — a ledger problem must never
    cost the driver its payload line or the rc=0 contract."""
    path = os.environ.get("STENCIL_BENCH_LEDGER")
    if not path:
        return
    try:
        ledger = _load_obs("ledger", "stencil_ledger")
        label = (os.environ.get(ledger.ENV_LABEL)
                 or time.strftime("bench-%Y%m%dT%H%M%S"))
        entries = ledger.entries_from_bench_payload(
            payload, label=label,
            rev=ledger.git_rev(os.path.dirname(os.path.abspath(__file__))),
            source="bench")
        n = ledger.append_entries(path, entries)
        print(f"[bench] ledger: +{n} entries ({label}) -> {path}",
              file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001 — evidence, never the measurement
        print(f"[bench] ledger append failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)


def _parse_sentinel(stdout: str) -> dict | None:
    payload = None
    for line in stdout.splitlines():
        if line.startswith(SENTINEL):
            try:
                payload = json.loads(line[len(SENTINEL):])
            except json.JSONDecodeError:
                payload = None
    return payload


def main() -> int:
    watchdog = _load_watchdog()
    budget_s = float(os.environ.get("STENCIL_BENCH_BUDGET_S", "900"))
    # stall deadline: generous — a leg can sit in a single XLA compile for
    # minutes, and a compile that holds the interpreter also pauses the
    # child's beat thread (that pause must not read as a wedge)
    heartbeat_s = float(os.environ.get("STENCIL_BENCH_HEARTBEAT_S", "300"))
    rev = watchdog.Revival(
        budget_s=budget_s,
        parse=_parse_sentinel,
        archive_dir=os.environ.get("STENCIL_BENCH_LOG_DIR") or None,
    )

    def child(mode: str, timeout_s: float, floor_s: float = 0.0,
              resume: bool = False):
        env = dict(os.environ)
        env["STENCIL_BENCH_LEG_BUDGET_S"] = str(max(60.0, timeout_s - 60.0))
        # resume-on-revival: every rung after the first tells the child to
        # continue from its last durable checkpoint (no-op without
        # STENCIL_BENCH_CKPT_DIR; elastic restore skips an incompatible
        # snapshot, so the smaller CPU fallback still starts clean)
        cmd = [sys.executable, os.path.abspath(__file__), "--child", mode]
        if resume:
            cmd.append("--resume")
        return rev.attempt(
            f"bench-{mode}",
            cmd,
            timeout_s=timeout_s,
            heartbeat_timeout_s=heartbeat_s,
            env=env,
            floor_timeout_s=floor_s,
        )

    # schedule: accel try 1 (bulk of the budget), backoff, accel try 2,
    # forced-CPU fallback (reserved slice), static last resort. Every
    # floor is bounded by the budget itself so the total stays within
    # ~budget + one minimal CPU try (a driver that kills at the stated
    # budget must not be starved of the JSON line by our own floors).
    # accel attempt 1 gets the lion's share: the astaroth 512^3 leg's gate
    # needs ~260s left in the child after the earlier legs (~280s), so a
    # 900s default budget must translate to a >=540s first-try leg budget
    reserve_cpu = min(180.0, max(30.0, budget_s * 0.25))
    avail = max(0.0, budget_s - reserve_cpu - 10.0)
    plan = [("accel", avail * 0.85), ("accel", avail * 0.15)]
    for i, (mode, timeout_s) in enumerate(plan):
        if i > 0:
            rev.backoff(20.0, floor_s=reserve_cpu)
        timeout_s = min(timeout_s, max(10.0, rev.remaining() - reserve_cpu))
        if timeout_s < 10.0:
            continue  # not enough time to even import jax
        payload = child(mode, timeout_s, resume=i > 0)
        if payload is not None:
            print(json.dumps(payload), flush=True)
            _append_ledger(payload)
            return 0
    payload = child("cpu", max(30.0, rev.remaining() - 5.0), floor_s=30.0,
                    resume=True)
    if payload is not None:
        print(json.dumps(payload), flush=True)
        _append_ledger(payload)
        return 0
    # last resort: the driver still gets its one line and rc=0; the
    # attempt ladder (outcomes, archived logs) goes to stderr as evidence
    print(f"[bench] all children failed; attempts: "
          f"{json.dumps(rev.report())}", file=sys.stderr, flush=True)
    payload = {
        "metric": "jacobi3d_512_mcells_per_s_per_chip",
        "value": 0.0,
        "unit": "Mcells/s",
        "vs_baseline": 0.0,
        "detail": {"error": "all bench children failed; see stderr"},
    }
    print(json.dumps(payload), flush=True)
    # the outage round must land in the ledger too — the trend shows the
    # zero instead of skipping the round (the r03 discipline)
    _append_ledger(payload)
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        raise SystemExit(_child_main(sys.argv[2],
                                     resume="--resume" in sys.argv[3:]))
    raise SystemExit(main())
