// Native quadratic-assignment solvers for stencil_tpu.
//
// C++ re-implementation of the plan-time QAP machinery (reference:
// include/stencil/qap.hpp — exhaustive next_permutation search with a
// wall-clock timeout, and greedy best-pairwise-swap descent with
// incremental cost updates). Exposed through a plain C ABI consumed via
// ctypes (stencil_tpu/native/__init__.py); semantics match the Python
// fallback in stencil_tpu/parallel/qap.py exactly (0 * inf counts as 0).
//
// Within the same 10 s budget this explores ~100x more permutations than
// CPython, which materially improves exact placements for n >= 9.

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

inline double cost_product(double we, double de) {
  if (we == 0.0 || de == 0.0) return 0.0;
  return we * de;
}

inline double cost(int n, const double *w, const double *d,
                   const std::size_t *f) {
  double ret = 0.0;
  for (int a = 0; a < n; ++a) {
    const double *wrow = w + static_cast<std::size_t>(a) * n;
    const double *drow = d + f[a] * n;
    for (int b = 0; b < n; ++b) {
      ret += cost_product(wrow[b], drow[f[b]]);
    }
  }
  return ret;
}

} // namespace

extern "C" {

// Exhaustive permutation search from the identity, bounded by timeout_s.
// Returns 1 if the search timed out before exhausting all permutations.
int stencil_qap_solve(int n, const double *w, const double *d,
                      double timeout_s, std::size_t *out_f, double *out_cost) {
  using Clock = std::chrono::steady_clock;
  const auto stop =
      Clock::now() + std::chrono::duration_cast<Clock::duration>(
                         std::chrono::duration<double>(timeout_s));

  std::vector<std::size_t> f(n);
  for (int i = 0; i < n; ++i) f[i] = i;
  std::vector<std::size_t> best = f;
  double best_cost = cost(n, w, d, f.data());
  int timed_out = 0;

  std::uint64_t iter = 0;
  do {
    // amortize the clock read; a cost() evaluation is O(n^2)
    if ((++iter & 0x3ff) == 0 && Clock::now() > stop) {
      timed_out = 1;
      break;
    }
    const double c = cost(n, w, d, f.data());
    if (c < best_cost) {
      best_cost = c;
      best = f;
    }
  } while (std::next_permutation(f.begin(), f.end()));

  std::copy(best.begin(), best.end(), out_f);
  if (out_cost) *out_cost = best_cost;
  return timed_out;
}

// Greedy best-pairwise-swap descent (reference: qap.hpp:87-180).
//
// The incremental cost update accumulates floating-point drift, so a swap
// between symmetric (equal-cost) assignments can look like an
// epsilon-improvement forever; improvements must clear a relative epsilon
// to count (the reference algorithm loops indefinitely on such inputs).
int stencil_qap_solve_catch(int n, const double *w, const double *d,
                            std::size_t *out_f, double *out_cost) {
  const double kRelEps = 1e-12;
  std::vector<std::size_t> best(n);
  for (int i = 0; i < n; ++i) best[i] = i;
  double best_cost = cost(n, w, d, best.data());

  auto pair_cost = [&](int a, int b, std::size_t fa, std::size_t fb) {
    return cost_product(w[static_cast<std::size_t>(a) * n + b], d[fa * n + fb]);
  };

  bool improved = true;
  while (improved) {
    improved = false;
    std::vector<std::size_t> impr = best;
    double impr_cost = best_cost;
    for (int i = 0; i < n; ++i) {
      for (int j = i + 1; j < n; ++j) {
        std::vector<std::size_t> f = best;
        double c = best_cost;
        for (int k = 0; k < n; ++k) {
          c -= pair_cost(i, k, f[i], f[k]);
          c -= pair_cost(j, k, f[j], f[k]);
          if (k != i && k != j) {
            c -= pair_cost(k, i, f[k], f[i]);
            c -= pair_cost(k, j, f[k], f[j]);
          }
        }
        std::swap(f[i], f[j]);
        for (int k = 0; k < n; ++k) {
          c += pair_cost(i, k, f[i], f[k]);
          c += pair_cost(j, k, f[j], f[k]);
          if (k != i && k != j) {
            c += pair_cost(k, i, f[k], f[i]);
            c += pair_cost(k, j, f[k], f[j]);
          }
        }
        if (c < impr_cost - kRelEps * (1.0 + std::abs(impr_cost))) {
          impr = f;
          impr_cost = c;
          improved = true;
        }
      }
    }
    if (improved) {
      best = impr;
      best_cost = impr_cost;
    }
  }

  std::copy(best.begin(), best.end(), out_f);
  if (out_cost) *out_cost = best_cost;
  return 0;
}

} // extern "C"
