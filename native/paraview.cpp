// Native CSV row writer for DistributedDomain.write_paraview.
//
// The reference writes its paraview dumps from C++ (src/stencil.cu:1188-1264);
// the Python row loop is O(cells) interpreter work — minutes at flagship
// sizes where this writer streams ~10^8 rows in seconds. C ABI via ctypes
// (same pattern as qap.cpp); float formatting is std::to_chars shortest
// round-trip, normalized to Python's repr() ("2" -> "2.0") so the native
// and fallback paths emit byte-identical files.

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

// Append v formatted EXACTLY like Python's repr(float): shortest
// round-trip digits, fixed notation iff the decimal exponent E is in
// [-4, 16), else scientific with a signed >=2-digit exponent. (A plain
// std::to_chars general format picks fixed-vs-scientific by string
// length instead — 0.0001 would become "1e-04".)
inline char *fmt_double(char *p, double v) {
    if (std::isnan(v)) {
        std::memcpy(p, "nan", 3);
        return p + 3;
    }
    if (std::isinf(v)) {
        if (v < 0) *p++ = '-';
        std::memcpy(p, "inf", 3);
        return p + 3;
    }
    if (std::signbit(v)) {
        *p++ = '-';
        v = -v;
    }
    char buf[48];  // shortest scientific: "d[.ddd]e±dd"
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
    const char *end =
        std::to_chars(buf, buf + sizeof buf, v, std::chars_format::scientific)
            .ptr;
#else
    // libstdc++ < GCC 11 ships integer-only to_chars. Shortest round-trip
    // by precision search instead: %.*e rounds to the CLOSEST (p+1)-digit
    // scientific string, so the first precision whose strtod round-trips
    // is exactly the shortest-round-trip digit string to_chars picks.
    int len = 0;
    for (int prec = 0; prec <= 17; ++prec) {
        len = std::snprintf(buf, sizeof buf, "%.*e", prec, v);
        if (std::strtod(buf, nullptr) == v) break;
    }
    const char *end = buf + len;
#endif
    char digits[40];
    int nd = 0;
    const char *q = buf;
    digits[nd++] = *q++;
    // ',' too: the snprintf fallback is locale-dependent where to_chars
    // is not, and a comma-decimal LC_NUMERIC must not corrupt the scan
    if (*q == '.' || *q == ',') {
        ++q;
        while (*q != 'e') digits[nd++] = *q++;
    }
    ++q;  // 'e'
    const int esign = (*q == '-') ? -1 : 1;
    ++q;
    int E = 0;
    while (q < end) E = E * 10 + (*q++ - '0');
    E *= esign;
    if (E >= -4 && E < 16) {  // fixed
        if (E >= nd - 1) {
            for (int i = 0; i < nd; ++i) *p++ = digits[i];
            for (int i = nd - 1; i < E; ++i) *p++ = '0';
            *p++ = '.';
            *p++ = '0';
        } else if (E >= 0) {
            for (int i = 0; i <= E; ++i) *p++ = digits[i];
            *p++ = '.';
            for (int i = E + 1; i < nd; ++i) *p++ = digits[i];
        } else {
            *p++ = '0';
            *p++ = '.';
            for (int i = 0; i < -E - 1; ++i) *p++ = '0';
            for (int i = 0; i < nd; ++i) *p++ = digits[i];
        }
    } else {  // scientific, Python style
        *p++ = digits[0];
        if (nd > 1) {
            *p++ = '.';
            for (int i = 1; i < nd; ++i) *p++ = digits[i];
        }
        *p++ = 'e';
        *p++ = (E < 0) ? '-' : '+';
        int a = (E < 0) ? -E : E;
        char eb[8];
        int ne = 0;
        while (a) {
            eb[ne++] = char('0' + a % 10);
            a /= 10;
        }
        while (ne < 2) eb[ne++] = '0';
        while (ne) *p++ = eb[--ne];
    }
    return p;
}

inline char *fmt_long(char *p, int64_t v) {
    auto res = std::to_chars(p, p + 24, v);
    return res.ptr;
}

}  // namespace

extern "C" int stencil_paraview_write(
    const char *path, const char *header,
    int64_t oz, int64_t oy, int64_t ox,   // block's global origin (z, y, x)
    int64_t sz, int64_t sy, int64_t sx,   // interior extent
    int nq, const double *const *qs) {    // nq dense [sz, sy, sx] arrays
    FILE *f = std::fopen(path, "w");
    if (!f) return -1;
    std::vector<char> iobuf(size_t(1) << 20);
    std::setvbuf(f, iobuf.data(), _IOFBF, iobuf.size());
    std::fputs(header, f);
    std::fputc('\n', f);
    // worst case per row: 3 int64 + nq doubles + separators
    std::vector<char> line(size_t(80) + size_t(nq) * 40);
    for (int64_t z = 0; z < sz; ++z) {
        for (int64_t y = 0; y < sy; ++y) {
            const int64_t row0 = (z * sy + y) * sx;
            for (int64_t x = 0; x < sx; ++x) {
                char *p = line.data();
                p = fmt_long(p, oz + z);
                *p++ = ',';
                p = fmt_long(p, oy + y);
                *p++ = ',';
                p = fmt_long(p, ox + x);
                for (int q = 0; q < nq; ++q) {
                    *p++ = ',';
                    p = fmt_double(p, qs[q][row0 + x]);
                }
                *p++ = '\n';
                if (std::fwrite(line.data(), 1, size_t(p - line.data()), f)
                    != size_t(p - line.data())) {
                    std::fclose(f);
                    return -2;
                }
            }
        }
    }
    return std::fclose(f) == 0 ? 0 : -3;
}
